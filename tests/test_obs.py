"""Observability subsystem: span trees, traceparent propagation, engine
step telemetry, and the flight recorder — across the streaming,
speculative, preemption, and multihost-mirror paths (ISSUE 3 acceptance:
every dumped trace must be well-formed — single root, no orphan/unclosed
spans — and tracing must be off the hot path when disabled)."""

import queue
import threading
import time

import httpx
import pytest

import jax  # noqa: F401  (platform pinned in conftest before backends init)

from scalable_hw_agnostic_inference_tpu.obs import (
    BucketHistogram,
    FlightRecorder,
    StepTelemetry,
)
from scalable_hw_agnostic_inference_tpu.obs import trace as obs_trace
from scalable_hw_agnostic_inference_tpu.obs.trace import (
    well_formed_problems,
)

from test_engine import make_engine, tiny_model  # noqa: F401 (fixture)
from test_serve_http import EchoService, make_cfg, make_client, wait_ready


# ---------------------------------------------------------------------------
# trace primitives
# ---------------------------------------------------------------------------

def test_traceparent_parse_and_format():
    tid, sid = "ab" * 16, "cd" * 8
    hdr = obs_trace.format_traceparent(tid, sid)
    assert obs_trace.parse_traceparent(hdr) == (tid, sid)
    assert obs_trace.parse_traceparent(None) is None
    assert obs_trace.parse_traceparent("garbage") is None
    assert obs_trace.parse_traceparent("00-" + "0" * 32 + "-" + sid + "-01") \
        is None  # all-zero trace id is invalid per spec
    assert obs_trace.parse_traceparent(f"00-{tid}-{'0' * 16}-01") is None


def test_span_nesting_builds_tree_via_contextvars():
    tr = obs_trace.Trace("root-op")
    with obs_trace.use_trace(tr):
        with obs_trace.span("outer") as outer:
            with obs_trace.span("inner", k=1) as inner:
                pass
    tr.close()
    d = tr.to_dict()
    assert not well_formed_problems(d), well_formed_problems(d)
    by_name = {s["name"]: s for s in d["spans"]}
    assert by_name["outer"]["parent_id"] == by_name["root-op"]["span_id"]
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["attrs"]["k"] == 1
    assert inner.span.closed and outer.span.closed


def test_add_span_from_other_thread_is_safe():
    tr = obs_trace.Trace("op")
    t0 = time.monotonic()

    def engine_side():
        tr.add_span("decode", t0, t0 + 0.01, phase=True)

    t = threading.Thread(target=engine_side)
    t.start()
    t.join()
    tr.close()
    d = tr.to_dict()
    assert not well_formed_problems(d)
    decode = next(s for s in d["spans"] if s["name"] == "decode")
    assert decode["parent_id"] == tr.root.span_id
    assert decode["duration_s"] == pytest.approx(0.01, abs=1e-3)


def test_well_formed_detects_orphans_unclosed_and_multiroot():
    assert well_formed_problems({"spans": []})
    # orphan parent
    bad = {"spans": [
        {"name": "r", "span_id": "a", "parent_id": None, "duration_s": 0.1},
        {"name": "x", "span_id": "b", "parent_id": "zz", "duration_s": 0.1},
    ]}
    assert any("orphan" in p for p in well_formed_problems(bad))
    # unclosed
    bad = {"spans": [
        {"name": "r", "span_id": "a", "parent_id": None, "duration_s": -1.0},
    ]}
    assert any("unclosed" in p for p in well_formed_problems(bad))
    # two roots
    bad = {"spans": [
        {"name": "r", "span_id": "a", "parent_id": None, "duration_s": 0.1},
        {"name": "q", "span_id": "b", "parent_id": None, "duration_s": 0.1},
    ]}
    assert any("one root" in p for p in well_formed_problems(bad))
    # a crashed handler's span is force-closed by Trace.close AND reported
    tr = obs_trace.Trace("op")
    live = tr.span("leaky")
    live.__enter__()  # never exited
    tr.close()
    assert any("force-closed" in p
               for p in well_formed_problems(tr.to_dict()))


def test_span_tree_fuzz_always_well_formed():
    """Randomized span workloads — nested context spans, handler
    exceptions mid-span, concurrent engine-side add_span from worker
    threads, random phase grafts — must ALWAYS dump a well-formed tree
    (single root, no orphans, no unclosed spans)."""
    import random

    rng = random.Random(1337)
    for trial in range(30):
        tr = obs_trace.Trace(f"op-{trial}")

        def nested(depth: int) -> None:
            if depth <= 0 or rng.random() < 0.3:
                return
            try:
                with obs_trace.span(f"d{depth}-{rng.randrange(4)}"):
                    if rng.random() < 0.2:
                        raise ValueError("handler blew up mid-span")
                    nested(depth - 1)
            except ValueError:
                pass  # the span context must still have closed itself

        def engine_side() -> None:
            t0 = time.monotonic()
            for i in range(rng.randrange(1, 4)):
                tr.add_span(f"phase{i}", t0, t0 + rng.random() * 0.01)
            if rng.random() < 0.5:
                tr.add_phase_spans({"t_submit": t0, "t_admit": t0 + 0.001,
                                    "t_first": t0 + 0.002,
                                    "t_done": t0 + 0.003})

        with obs_trace.use_trace(tr):
            threads = [threading.Thread(target=engine_side)
                       for _ in range(rng.randrange(0, 3))]
            for t in threads:
                t.start()
            nested(rng.randrange(1, 6))
            for t in threads:
                t.join()
        tr.close()
        d = tr.to_dict()
        assert not well_formed_problems(d), (trial, well_formed_problems(d))


def test_tracing_disabled_is_off_the_hot_path():
    obs_trace.configure(False)
    try:
        assert obs_trace.begin_request_trace("x") is None
        s = obs_trace.span("y")
        assert s is obs_trace.NOOP  # shared constant: zero allocation
        with s:
            pass
        assert obs_trace.annotate("z") is obs_trace.NOOP
        # and with no active trace (tracing on), span() is STILL the noop
        obs_trace.configure(True)
        assert obs_trace.current_trace() is None
        assert obs_trace.span("y") is obs_trace.NOOP
    finally:
        obs_trace.configure(True)


# ---------------------------------------------------------------------------
# step telemetry + flight recorder primitives
# ---------------------------------------------------------------------------

def test_bucket_histogram_cumulative_shape():
    h = BucketHistogram((0.1, 1.0))
    for v in (0.05, 0.5, 0.7, 5.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(6.25)
    assert s["buckets"] == [(0.1, 1), (1.0, 3), ("+Inf", 4)]


def test_step_telemetry_ring_is_bounded():
    t = StepTelemetry(total_blocks=10, max_steps=4)
    for i in range(9):
        t.record_step(kind="decode", duration_s=0.01, n_running=1,
                      n_waiting=i, n_chunking=0, blocks_free=5)
    recs = t.recent_steps()
    assert len(recs) == 4
    assert recs[-1]["step"] == 9 and recs[-1]["waiting"] == 8
    assert recs[-1]["kv_utilization"] == 0.5
    snap = t.snapshot()
    assert snap["steps"] == 9 and snap["waiting"] == 8.0
    t.count_preemption()
    t.count_recompile("decode")
    snap = t.snapshot()
    assert snap["preemptions"] == 1 and snap["recompiles"] == 1


def test_flight_recorder_ring_and_dump():
    fr = FlightRecorder(max_requests=3, max_steps=2)
    for i in range(5):
        fr.record_request({"trace_id": f"t{i}", "spans": []})
    d = fr.dump(step_source=lambda n: [{"step": 1}][:n])
    assert d["recorded_total"] == 5
    assert [r["trace"]["trace_id"] for r in d["requests"]] == \
        ["t2", "t3", "t4"]
    # the trace id rides at the record's top level (the trace-join key)
    assert [r["trace_id"] for r in d["requests"]] == ["t2", "t3", "t4"]
    assert d["engine_steps"] == [{"step": 1}]

    def boom(n):
        raise RuntimeError("engine gone")

    d = fr.dump(step_source=boom)
    assert "engine gone" in d["engine_steps_error"]
    assert d["requests"]  # the request ring still dumps
    # n_requests edge cases: 0 means zero (reqs[-0:] would be ALL), and
    # asking past the ring returns what exists
    assert fr.dump(n_requests=0)["requests"] == []
    assert len(fr.dump(n_requests=99)["requests"]) == 3


# ---------------------------------------------------------------------------
# engine integration: speculative + preemption paths
# ---------------------------------------------------------------------------

def test_spec_engine_emits_timing_and_step_records(tiny_model):
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        SamplingParams,
    )

    eng = make_engine(tiny_model, speculative_model="[ngram]",
                      num_speculative_tokens=3)
    base = [1, 5, 9, 11, 7, 3, 2, 8]
    prompt = (base * 3)[:20]  # repetitive: the n-gram drafter fires
    fins = eng.generate([prompt, prompt],
                        SamplingParams(temperature=0.0, max_new_tokens=10))
    assert all(f.stop_reason == "length" for f in fins)
    for f in fins:
        t = f.timing
        assert t is not None
        assert t["queue_s"] >= 0 and t["prefill_s"] >= 0
        assert t["decode_s"] >= 0
        assert t["total_s"] == pytest.approx(
            t["t_done"] - t["t_submit"], abs=1e-4)
    recs = eng.obs.recent_steps()
    assert recs, "no step records"
    kinds = {r["kind"] for r in recs}
    assert "spec" in kinds, kinds  # the speculative path actually ran
    assert any("spec" in r for r in recs)  # spec counters ride the records
    snap = eng.obs.snapshot()
    assert snap["steps"] == len(recs) == eng._step_count
    assert snap["ttft_count"] == 2 and snap["queue_wait_count"] == 2
    assert snap["spec_acceptance_rate"] >= 0.0


@pytest.mark.slow  # tier-1 preemption coverage: test_engine.py pressure
# test + test_engine_async.py differential (PR 6 budget trade)
def test_preemption_path_counts_and_keeps_timing(tiny_model):
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        SamplingParams,
    )

    # 3 seqs x 3 blocks each at full length = 9 > the 6 usable blocks:
    # growth MUST preempt at least once before all three finish
    eng = make_engine(tiny_model, num_blocks=7)
    prompts = [[1, 5, 9, 11], [1, 200, 300], [2, 7, 9, 13, 15]]
    fins = eng.generate(prompts, SamplingParams(temperature=0.0,
                                                max_new_tokens=16))
    assert [f.stop_reason for f in fins] == ["length"] * 3
    assert all(len(f.token_ids) == 16 for f in fins)
    assert eng.obs.preemptions >= 1
    assert eng.obs.recent_steps()[-1]["preemptions_total"] == \
        eng.obs.preemptions
    for f in fins:  # preempted-and-resumed requests keep ONE timeline
        assert f.timing is not None
        assert f.timing["t_done"] >= f.timing["t_first"] >= \
            f.timing["t_admit"] >= f.timing["t_submit"]


def test_resumed_request_timing_uses_original_first_token(tiny_model):
    """A preemption resume carries the request-level t_first: the timeline
    must book the pre-preemption decode segment (and the re-queue wait)
    under decode_s, not prefill_s — the slot-level t_first passed by the
    finish sites is the RESUMED segment's and would do exactly that."""
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        Request,
        SamplingParams,
    )

    eng = make_engine(tiny_model)
    now = time.monotonic()
    req = Request(0, [1, 2, 3], SamplingParams(max_new_tokens=4),
                  already_generated=[5, 6],  # marks a resume
                  t_submit=now - 10.0, t_admit=now - 9.5, t_first=now - 9.0)
    t = eng._timing_of(req, t_first=now - 1.0)  # resumed segment's stamp
    assert t["t_first"] == req.t_first
    assert t["prefill_s"] == pytest.approx(0.5, abs=0.1)
    assert t["decode_s"] >= 8.9  # segment 1 + re-queue + segment 2


def test_rejected_request_books_wait_as_queue_not_decode(tiny_model):
    """A request finished straight from the waiting queue (never admitted)
    spent its whole life in queue_s — missing stamps must fall FORWARD,
    not book the wait into a decode phase that never ran."""
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        SamplingParams,
    )

    # pool of 4 blocks (3 usable) but a 32-token prompt needs 4 blocks
    eng = make_engine(tiny_model, num_blocks=4, max_num_seqs=1)
    [fin] = eng.generate([[1] * 32], SamplingParams(max_new_tokens=4))
    assert fin.stop_reason == "rejected"
    t = fin.timing
    assert t is not None
    assert t["prefill_s"] == 0.0 and t["decode_s"] == 0.0
    assert t["queue_s"] == pytest.approx(t["total_s"], abs=1e-4)


def test_post_warm_executable_build_counts_as_recompile(tiny_model):
    eng = make_engine(tiny_model)
    eng._decode_for(1, 1)
    assert eng.obs.recompiles == 0  # pre-warm builds are the closed set
    eng._warmed = True
    eng._decode_for(1, 2)
    eng._prefill_for(16, 0, 2)
    assert eng.obs.recompiles == 2


def test_cache_shrink_counts_rollback_tokens():
    import jax.numpy as jnp

    from scalable_hw_agnostic_inference_tpu.engine.cache import PagedKVCache

    c = PagedKVCache(1, 1, 4, total_blocks=8, block_size=4,
                     blocks_per_seq=4, dtype=jnp.float32)
    c.admit(0, 10)  # 3 blocks
    c.extend(0, 4)  # reserve like a spec step would
    c.shrink(0, 3)  # reject 3 drafted tokens
    assert c.rollback_tokens == 3
    assert c.rollback_calls == 1
    c.shrink(0, 0)  # no-op shrink does not count
    assert c.rollback_calls == 1


# ---------------------------------------------------------------------------
# serving integration: vllm unit with speculative decoding
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spec_app():
    """Tiny engine-backed service with speculative decoding on — ONE
    warmed service shared by every HTTP-level obs test in this module."""
    import dataclasses

    from scalable_hw_agnostic_inference_tpu.models.registry import get_model
    from scalable_hw_agnostic_inference_tpu.serve.app import create_app
    from scalable_hw_agnostic_inference_tpu.utils.env import ServeConfig

    cfg = ServeConfig(app="llm-obs", model_id="tiny", device="cpu",
                      max_new_tokens=16, vllm_config="/nonexistent.yaml")
    service = get_model("vllm")(cfg)
    # smallest closed executable set that still exercises every obs path
    # (2 slots batch the concurrent tests; serial prefill halves the warm
    # ladder — this fixture is the costliest compile in the obs suite)
    service.ecfg = dataclasses.replace(
        service.ecfg, speculative_model="[ngram]", num_speculative_tokens=3,
        max_num_seqs=2, max_prefill_batch=1)
    return cfg, service, create_app(cfg, service)


@pytest.mark.slow  # tier-1 budget: see scripts/check_tier1_budget.py
@pytest.mark.asyncio
async def test_http_traceparent_ingest_emit_and_flight(spec_app):
    cfg, service, app = spec_app
    upstream = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    async with make_client(app) as c:
        r = await wait_ready(c, timeout=600.0)
        assert r.status_code == 200, r.text
        r = await c.post("/generate",
                         json={"prompt": "to be or not to be or not",
                               "temperature": 0.0, "max_new_tokens": 6},
                         headers={"traceparent": upstream})
        assert r.status_code == 200, r.text
        # W3C emit: same trace id, OUR root span id
        tp = r.headers["traceparent"]
        assert tp.split("-")[1] == "ab" * 16
        assert tp.split("-")[2] != "cd" * 8

        r = await c.get("/debug/flight")
        d = r.json()
        traces = [q["trace"] for q in d["requests"]
                  if q["trace"]["name"] == "POST /generate"]
        assert traces, "generate request missing from the flight ring"
        tr = traces[-1]
        assert tr["trace_id"] == "ab" * 16
        assert tr["remote_parent"] == "cd" * 8
        assert not well_formed_problems(tr), well_formed_problems(tr)
        names = {s["name"] for s in tr["spans"]}
        # the full timeline: http root, model lane, tokenize/detokenize,
        # and the engine's queue/prefill/decode phase spans
        assert {"POST /generate", "model_infer", "tokenize", "queue",
                "prefill", "decode", "detokenize"} <= names
        # engine step records ride the same dump
        assert d["engine_steps"], "no engine step records"
        last = d["engine_steps"][-1]
        assert {"kind", "running", "waiting", "kv_utilization",
                "preemptions_total", "recompiles_total"} <= set(last)
        # probes are excluded from the ring (readiness polls above)
        assert all(q["trace"]["name"] != "GET /readiness"
                   for q in d["requests"])


@pytest.mark.asyncio
async def test_streaming_request_trace_is_well_formed(spec_app):
    cfg, service, app = spec_app
    async with make_client(app) as c:
        await wait_ready(c, timeout=600.0)
        async with c.stream(
                "POST", "/v1/completions",
                json={"prompt": "a b c a b c a b", "stream": True,
                      "temperature": 0.0, "max_tokens": 5}) as r:
            assert r.status_code == 200
            body = ""
            async for chunk in r.aiter_text():
                body += chunk
        assert "data: [DONE]" in body

        d = (await c.get("/debug/flight")).json()
        traces = [q["trace"] for q in d["requests"]
                  if q["trace"]["name"] == "POST /v1/completions"]
        assert traces, "streaming request missing from the flight ring"
        tr = traces[-1]
        assert not well_formed_problems(tr), well_formed_problems(tr)
        names = {s["name"] for s in tr["spans"]}
        assert {"queue", "prefill", "decode"} <= names
        # the root span covers the stream DRAIN, so it must be at least as
        # long as the engine's decode phase
        root = next(s for s in tr["spans"] if s["parent_id"] is None)
        decode = next(s for s in tr["spans"] if s["name"] == "decode")
        assert root["duration_s"] >= decode["duration_s"] - 0.05


@pytest.mark.asyncio
async def test_metrics_exposes_engine_histograms_and_gauges(spec_app):
    pytest.importorskip("prometheus_client")
    cfg, service, app = spec_app
    async with make_client(app) as c:
        await wait_ready(c, timeout=600.0)
        await c.post("/generate", json={"prompt": "x y z x y z",
                                        "temperature": 0.0,
                                        "max_new_tokens": 4})
        r = await c.get("/metrics")
        assert r.status_code == 200
        for name in ("shai_ttft_seconds_bucket", "shai_ttft_seconds_sum",
                     "shai_tpot_seconds_bucket",
                     "shai_queue_wait_seconds_bucket",
                     "shai_engine_running", "shai_engine_waiting",
                     "shai_engine_kv_utilization",
                     "shai_engine_preemptions_total",
                     "shai_engine_recompiles_total",
                     "shai_spec_acceptance_rate"):
            assert name in r.text, f"{name} missing from /metrics"
        # histogram actually observed something
        assert 'shai_ttft_seconds_count{app="llm-obs"}' in r.text

        st = (await c.get("/stats")).json()
        assert st["engine"]["steps"] > 0
        assert "kv_utilization" in st["engine"]
        assert "exports" in st["aot"]


@pytest.mark.asyncio
async def test_disabled_tracing_serves_without_traces(spec_app):
    cfg, service, app = spec_app
    async with make_client(app) as c:
        await wait_ready(c, timeout=600.0)
        before = (await c.get("/debug/flight")).json()["recorded_total"]
        obs_trace.configure(False)
        try:
            r = await c.post("/generate",
                             json={"prompt": "hello hello hello",
                                   "temperature": 0.0, "max_new_tokens": 4})
            assert r.status_code == 200, r.text
            assert "traceparent" not in r.headers
        finally:
            obs_trace.configure(True)
        after = (await c.get("/debug/flight")).json()["recorded_total"]
        assert after == before  # nothing recorded while disabled


# ---------------------------------------------------------------------------
# plain (engine-less) service still traces; multihost mirror propagation
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_engineless_service_traces_and_empty_steps():
    from scalable_hw_agnostic_inference_tpu.serve.app import create_app

    cfg = make_cfg()
    app = create_app(cfg, EchoService(cfg))
    async with make_client(app) as c:
        await wait_ready(c)
        r = await c.post("/predict", json={"text": "hi"})
        assert "traceparent" in r.headers
        await c.get("/stats")  # scrape surface: must stay out of the ring
        # unrouted traffic (scanner 404s) still gets a traceparent but must
        # not turn over the postmortem ring
        r = await c.get("/wp-login.php")
        assert r.status_code == 404 and "traceparent" in r.headers
        d = (await c.get("/debug/flight")).json()
        assert d["engine_steps"] == []  # no engine, no step feed
        assert all(q["trace"]["name"] != "GET /stats" for q in d["requests"])
        assert all("/wp-login" not in q["trace"]["name"]
                   for q in d["requests"])
        tr = [q["trace"] for q in d["requests"]
              if q["trace"]["name"] == "POST /predict"][-1]
        assert not well_formed_problems(tr)
        assert {"POST /predict", "model_infer"} <= \
            {s["name"] for s in tr["spans"]}


def test_mirror_rpc_propagates_traceparent(monkeypatch):
    """Leader → follower over a faked coordination channel: the follower's
    mirrored call runs under the LEADER's trace id, and the follower-side
    trace is well-formed."""
    from scalable_hw_agnostic_inference_tpu.serve import multihost

    chan: "queue.Queue[bytes]" = queue.Queue()

    def fake_broadcast(payload):
        if payload is not None:
            chan.put(payload)
            return payload
        return chan.get(timeout=30)

    monkeypatch.setattr(multihost, "_broadcast_bytes", fake_broadcast)

    class Svc:
        mirror_methods = ("infer",)

        def __init__(self):
            self.seen = []

        def infer(self, payload):
            tr = obs_trace.current_trace()
            self.seen.append((payload,
                              None if tr is None else tr.trace_id))
            return {"ok": True}

    leader_svc, follower_svc = Svc(), Svc()
    follower_traces = []
    leader = multihost.MultihostDriver(leader_svc)
    follower = multihost.MultihostDriver(
        follower_svc, trace_sink=follower_traces.append)
    leader.wrap_leader()
    t = threading.Thread(target=follower.follower_loop, daemon=True)
    t.start()

    tr = obs_trace.Trace("POST /generate")
    with obs_trace.use_trace(tr):
        leader_svc.infer({"prompt": "x"})
    leader_svc.infer({"prompt": "untraced"})  # no active trace: still works
    leader.shutdown()
    t.join(timeout=30)
    assert not t.is_alive()
    tr.close()

    assert [p["prompt"] for p, _ in follower_svc.seen] == ["x", "untraced"]
    assert follower_svc.seen[0][1] == tr.trace_id  # leader's id, propagated
    assert len(follower_traces) == 2
    assert follower_traces[0]["trace_id"] == tr.trace_id
    assert follower_traces[0]["remote_parent"] == tr.root.span_id
    assert follower_traces[1]["trace_id"] != tr.trace_id  # fresh trace
    for ft in follower_traces:
        assert not well_formed_problems(ft), well_formed_problems(ft)
