# Repo gates. `make lint` is the one-stop static gate (AST + IR + docs +
# budget); `make lint-fast` suits pre-commit (pair with
# `python scripts/shai_lint.py --changed` for diff-scoped AST runs).

PY ?= python

.PHONY: lint lint-fast test

lint:
	$(PY) scripts/check_all.py

lint-fast:
	$(PY) scripts/check_all.py --fast

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
