# Repo gates. `make lint` is the one-stop static gate (AST + race + IR +
# docs + budget); `make lint-fast` suits pre-commit (pair with
# `python scripts/shai_lint.py --changed` for diff-scoped AST runs and
# `--race --changed` for diff-scoped race findings).

PY ?= python

.PHONY: lint lint-fast race test

lint:
	$(PY) scripts/check_all.py

lint-fast:
	$(PY) scripts/check_all.py --fast

race:
	$(PY) scripts/shai_lint.py --race

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
