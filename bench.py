"""Round-end benchmark: prints ONE JSON line for the driver — always.

Headline (default): SD2.1 512x512 txt2img on a single chip — real UNet/VAE
geometry (random weights; throughput is weight-value-independent), bf16, the
whole 25-step CFG denoise loop as one jitted scan. ``vs_baseline`` compares
single-stream images/sec against the reference's inf2.xlarge unit at its
published breaking point: latency 0.67 s/img => 1.49 img/s (BASELINE.md,
reference ``README.md:261``) — i.e. single-stream latency here vs the
reference's p50 *at* its breaking point, the comparison BASELINE.md records.

``python bench.py llama`` benches the causal-LM decode path instead
(Llama-3.2-1B geometry tokens/sec). ``--cpu`` forces tiny shapes on the CPU
platform (local smoke only).

Robustness contract (round-1 postmortem: BENCH_r01.json was a crash dump):
the parent process never touches the accelerator. It runs the measurement in
a child (``--inner``), retries backend init with backoff + stale-lock
cleanup, falls back to a CPU-tiny run if the TPU stays down, and in the
worst case still prints a well-formed JSON line with an ``error`` field and
exits 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

INNER = "--inner" in sys.argv

if INNER:
    import jax

    if "--cpu" in sys.argv:  # env-var JAX_PLATFORMS is captured too early
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

# inf2.xlarge SD2.1 breaking point: 0.67 s/img p50 (reference README.md:261)
SD_BASELINE_IMG_S = 1.0 / 0.67
#: one unit mapping for the measurement AND crash paths
UNITS_BY_BENCH = {"llama": "tokens/sec", "t5": "sequences/sec",
                  "mllama": "tokens/sec", "llama_spec": "tokens/sec",
                  "vllm": "tokens/sec", "kvtier": "x", "qos": "x",
                  "disagg": "x", "ragged": "tokens/sec",
                  "fused": "x", "migrate": "ms", "kvfabric": "x",
                  "scaler": "s", "hedge": "x",
                  "sd": "images/sec", "sd8": "images/sec",
                  "flux": "images/sec"}
# $/hr: v5e-1 on-demand (us-central, 1 chip) vs the reference's inf2.xlarge
# (reference README.md:192). The north star is throughput per DOLLAR, so
# every bench line carries the cost basis it was computed with.
V5E_COST_HR = 1.20
INF2_COST_HR = 0.7582


_ROOT = os.path.dirname(os.path.abspath(__file__))


def _pctl(xs, q):
    """Nearest-rank percentile over a small sample (ONE definition —
    bench_qos and bench_disagg must report p99 with identical math)."""
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1)))]



def _which_from_argv(argv) -> str:
    """THE argv->bench-key dispatch — one definition for the inner runner,
    the child arg forwarding, the banked-result lookup, main(), and the
    crash handler (five call sites that previously each hand-rolled it and
    drifted)."""
    if "llama_spec" in argv:  # before the llama prefix match below
        return "llama_spec"
    if any(a.startswith("llama") for a in argv):
        return "llama"
    for k in ("vllm", "kvtier", "qos", "disagg", "ragged", "fused",
              "migrate", "kvfabric", "scaler", "hedge", "flux", "t5",
              "mllama", "sd8"):
        if k in argv:
            return k
    return "sd"


def _published(key: str):
    """Self-baseline anchor from BASELINE.json.published (repo-root path —
    cwd-independent), or None before the first promoted on-chip run."""
    try:
        with open(os.path.join(_ROOT, "BASELINE.json")) as f:
            return json.load(f)["published"].get(key)
    except Exception:
        return None


def _phases_of(fins) -> dict:
    """Per-phase medians (seconds) across a batch of engine ``Finished``
    results — the queue/prefill/decode split from the obs timeline, attached
    to engine bench lines so a BENCH_*.json regression says WHERE the time
    went (queue wait vs prefill vs decode), not just that tok/s moved."""
    import statistics

    out = {}
    for k in ("queue_s", "prefill_s", "decode_s", "total_s"):
        vals = [f.timing[k] for f in fins
                if f.timing is not None and k in f.timing]
        if vals:
            out[k.replace("_s", "_s_p50")] = round(statistics.median(vals), 4)
    return out


def _dollars(out: dict, *, inf2_value: float | None = None) -> dict:
    """Attach the cost basis + work-per-dollar fields to a bench line.

    ``per_dollar`` is work units per dollar of chip time; when the reference
    publishes a comparable inf2 number, ``per_dollar_vs_inf2`` is the
    throughput/$ ratio (the BASELINE.md north star: >= 2.0).
    """
    out["chip_cost_per_hr"] = V5E_COST_HR
    out["per_dollar"] = round(out["value"] * 3600.0 / V5E_COST_HR, 2)
    if inf2_value is not None:
        out["per_dollar_vs_inf2"] = round(
            (out["value"] / V5E_COST_HR) / (inf2_value / INF2_COST_HR), 3)
    return out


def bench_sd8(tiny: bool) -> dict:
    """Batch-8 flash-attention throughput bench — the sd21-tpub8 serving
    tier's configuration (deploy/gen_units.py: SD_BATCH_MAX=8 +
    SHAI_ATTN_IMPL=pallas), driven through the coalescer's own
    txt2img_batch executable. This is the on-chip validation target for
    PERF_MODEL.md's headline projection (batch-8 + flash is the modeled
    path past 2x throughput/$ vs inf2)."""
    return bench_sd(tiny, batch=8, attn="pallas")


def bench_sd(tiny: bool, batch: int = 1, attn: str = "") -> dict:
    from scalable_hw_agnostic_inference_tpu.core.aot import (
        host_init,
        to_default_device,
    )
    from scalable_hw_agnostic_inference_tpu.models import sd as sd_mod

    if attn:
        # trace-time dispatch override (ops.attention): must be set before
        # the first pipeline build
        os.environ["SHAI_ATTN_IMPL"] = attn
    if tiny:
        variant, size, steps, seq = sd_mod.SDVariant.tiny(), 16, 2, 8
        attn = ""  # pallas kernels need a real TPU; tiny tier is CPU
        os.environ.pop("SHAI_ATTN_IMPL", None)
    else:
        variant, size, steps, seq = sd_mod.SDVariant.sd21_base(), 512, 25, 77

    unet = sd_mod.UNet2DCondition(variant.unet)
    f = 2 ** (len(variant.vae.block_out) - 1)
    lat = size // f
    from scalable_hw_agnostic_inference_tpu.models.convert import cast_f32_to_bf16

    # no eager device op before host_init: the first tunnel touch must be
    # the (cache-banked) forward compile, not a PRNGKey constant
    unet_params = host_init(
        unet.init, lambda: jax.random.PRNGKey(0),
        lambda: jnp.zeros((1, lat, lat, variant.unet.in_channels)),
        lambda: jnp.zeros((1,), jnp.int32),
        lambda: jnp.zeros((1, seq, variant.unet.cross_attention_dim)),
    )
    unet_params = to_default_device(cast_f32_to_bf16(unet_params))
    vae = sd_mod.AutoencoderKL(variant.vae)
    vae_params = to_default_device(host_init(
        vae.init, lambda: jax.random.PRNGKey(1),
        lambda: jnp.zeros((1, lat, lat, variant.vae.latent_channels))))
    rng = jax.random.PRNGKey(0)

    D = variant.unet.cross_attention_dim

    @jax.jit  # one dispatch for the stub conditioning (not benched)
    def text_encode(ids):  # conditioning cost is negligible; bench unet+vae
        return jax.nn.one_hot(ids % D, D, dtype=jnp.bfloat16)

    pipe = sd_mod.StableDiffusion(variant, unet_params, vae_params, text_encode)
    ids = jnp.zeros((1, seq), jnp.int32)

    if batch > 1:
        # the coalescer's own latents-as-argument executable, exactly as the
        # SD_BATCH_MAX serving tier runs it
        bids = jnp.zeros((batch, seq), jnp.int32)
        lats = jnp.concatenate(
            [pipe.init_latents(i, lat, lat, steps) for i in range(batch)])

        def run_batch():
            return pipe.txt2img_batch(bids, bids, lats, height=size,
                                      width=size, steps=steps)

        img = run_batch()  # warm (compiles the ('batch', B, ...) pipeline)
        runs = 3
        t0 = time.perf_counter()
        for _ in range(runs):
            img = run_batch()
        dt = (time.perf_counter() - t0) / runs
        assert img.shape[0] == batch and img.shape[1] == size
        label = f" b{batch}" + (f"-{attn}" if attn else "")
        return _dollars({
            "metric": f"sd21-{size}px {steps}-step{label} txt2img img/s "
                      f"({jax.devices()[0].platform})",
            "value": round(batch / dt, 4),
            "unit": "images/sec",
            "vs_baseline": round((batch / dt) / SD_BASELINE_IMG_S, 3),
        }, inf2_value=SD_BASELINE_IMG_S)

    stepwise = os.environ.get("SHAI_SD_STEPWISE", "") == "1"

    if not tiny:
        # staged warm: give the tunnel SMALL compiles first — the stepwise
        # single-step executable, then the VAE decode — before the
        # full-pipeline compile that wedged the r3 tunnel (VERDICT r3 weak
        # #7). Both are the REAL executables of stepwise mode, so this also
        # pre-banks the fallback path in the persistent XLA cache: if the
        # pipeline compile wedges the tunnel, the next attempt escalates to
        # SHAI_SD_STEPWISE=1 (see main()) and resumes these stages instantly.
        import numpy as np

        step = pipe._build_step(1)
        ts, a_t, a_p = (np.asarray(x) for x in pipe.scheduler.tables(steps))
        out = step(unet_params,
                   jnp.zeros((1, lat, lat, variant.unet.in_channels),
                             jnp.float32),
                   ts[0], a_t[0], a_p[0],
                   jnp.zeros((2, seq, D), jnp.bfloat16), jnp.float32(7.5))
        np.asarray(out).sum()
        print("warm stage 1/3 done (denoise step)", file=sys.stderr)
        np.asarray(pipe._decode(
            vae_params, jnp.zeros((1, lat, lat, variant.vae.latent_channels),
                                  jnp.float32))).sum()
        print("warm stage 2/3 done (vae decode)", file=sys.stderr)

    def run(key):
        if stepwise:
            # fallback for a tunnel that cannot survive the one-executable
            # pipeline compile: jitted single step in a host loop + jitted
            # decode. Async dispatch overlaps the per-step enqueues, so the
            # measured number stays comparable (mode is labeled).
            return pipe.txt2img_stepwise(ids, ids, rng=key, height=size,
                                         width=size, steps=steps)
        return pipe.txt2img(ids, ids, rng=key, height=size, width=size,
                            steps=steps)

    img = run(rng)  # warm stage 3/3: the full pipeline
    runs = 3
    t0 = time.perf_counter()
    for i in range(runs):
        img = run(jax.random.PRNGKey(i))
    dt = (time.perf_counter() - t0) / runs
    assert img.shape[1] == size
    mode = " stepwise" if stepwise else ""
    return _dollars({
        "metric": f"sd21-{size}px {steps}-step{mode} txt2img img/s "
                  f"({jax.devices()[0].platform})",
        "value": round(1.0 / dt, 4),
        "unit": "images/sec",
        "vs_baseline": round((1.0 / dt) / SD_BASELINE_IMG_S, 3),
    }, inf2_value=SD_BASELINE_IMG_S)


def bench_llama(tiny: bool) -> dict:
    from scalable_hw_agnostic_inference_tpu.models.generate import make_generate
    from scalable_hw_agnostic_inference_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    quant = "int8" in sys.argv
    if tiny:
        cfg, batch, prompt, new = LlamaConfig.tiny(), 2, 32, 16
        name = "tiny"
    elif "llama3b" in sys.argv:
        # the largest Llama that fits one v5e chip in bf16 with headroom
        cfg = LlamaConfig.llama32_3b()
        batch, prompt, new = 8, 128, 128
        name = "llama3.2-3b-geometry"
    else:
        cfg = LlamaConfig.llama32_1b()
        batch, prompt, new = 8, 128, 128
        name = "llama3.2-1b-geometry"

    from scalable_hw_agnostic_inference_tpu.core.aot import (
        host_init,
        to_default_device,
    )
    from scalable_hw_agnostic_inference_tpu.models.convert import cast_f32_to_bf16

    # init the float model on CPU; the int8 variant quantizes host-side
    # (the serving boot path: ops.quant.quantize_params_tree) and runs the
    # same geometry through QuantDense weights
    float_model = LlamaForCausalLM(cfg, dtype=jnp.bfloat16)
    params = host_init(float_model.init, lambda: jax.random.PRNGKey(0),
                       lambda: jnp.zeros((1, 8), jnp.int32))
    params = cast_f32_to_bf16(params)
    if quant:
        from scalable_hw_agnostic_inference_tpu.ops.quant import (
            quantize_params_tree,
        )

        params = quantize_params_tree(params)
        name += "-int8"
    params = to_default_device(params)
    rng = jax.random.PRNGKey(0)
    model = LlamaForCausalLM(cfg, dtype=jnp.bfloat16, quant=quant)
    gen = make_generate(model, cfg, prompt_bucket=prompt, max_new_tokens=new,
                        eos_id=-1)
    ids = jax.random.randint(rng, (batch, prompt), 3, cfg.vocab_size, jnp.int32)
    plen = jnp.full((batch,), prompt, jnp.int32)
    out = gen(params, ids, plen, rng, 1.0, 0, 1.0)
    out.tokens.block_until_ready()
    runs = 3
    t0 = time.perf_counter()
    for i in range(runs):
        out = gen(params, ids, plen, jax.random.fold_in(rng, i), 1.0, 0, 1.0)
    out.tokens.block_until_ready()
    dt = (time.perf_counter() - t0) / runs
    toks = batch * new / dt
    key = {"llama3.2-1b-geometry": "llama1b_decode_tok_s",
           "llama3.2-3b-geometry": "llama3b_decode_tok_s",
           "llama3.2-1b-geometry-int8": "llama1b_int8_decode_tok_s",
           "llama3.2-3b-geometry-int8": "llama3b_int8_decode_tok_s"}.get(name)
    base = _published(key)
    return _dollars({
        "metric": f"{name} decode tok/s (bs={batch}, "
                  f"{jax.devices()[0].platform})",
        "value": round(toks, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(toks / base, 3) if base else 1.0,
    })


def bench_llama_spec(tiny: bool) -> dict:
    """Speculative decoding tokens/sec through the paged engine: prompt-
    lookup ([ngram]) drafting with num_speculative_tokens=4, verified by the
    multi-token executable (engine/runner.py make_verify) — the PR-1
    tentpole's measured number. The workload is repetitive prompts (the
    regime prompt lookup targets: extraction/summarization-style requests
    whose output quotes the input); the line carries the realized
    acceptance_rate and tokens_per_verify so the perf model's
    acceptance-dependent projection (perf/model.py spec_decode_model) can be
    checked against an on-chip measurement, not just the roofline.
    """
    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=128, max_num_seqs=2, block_size=8,
                            context_encoding_buckets=(32,),
                            max_new_tokens=32,
                            speculative_model="[ngram]",
                            num_speculative_tokens=4)
        batch, prompt_len, new = 2, 24, 24
        name = "llama-tiny-spec"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=4,
                            block_size=16, context_encoding_buckets=(128,),
                            max_new_tokens=128,
                            speculative_model="[ngram]",
                            num_speculative_tokens=4)
        batch, prompt_len, new = 4, 128, 128
        name = "llama3.2-1b-geometry-spec"

    params = llama_mod.geometry_params(cfg, quant=False)
    eng = LLMEngine(cfg, params, ecfg)
    rng = np.random.default_rng(0)
    base = rng.integers(3, cfg.vocab_size, 16).tolist()
    prompt = (base * ((prompt_len // 16) + 1))[:prompt_len]
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    def run():
        for _ in range(batch):
            eng.add_request(prompt, sp)
        fins = []
        while eng.has_work:
            fins += eng.step()
        assert len(fins) == batch
        assert all(len(f.token_ids) == new for f in fins)
        return fins

    run()   # warm: prefill + decode + verify executables
    runs = 3
    fins = []
    t0 = time.perf_counter()
    for _ in range(runs):
        fins = run()
    dt = (time.perf_counter() - t0) / runs
    val = round(batch * new / dt, 2)
    base_v = _published("llama_spec_tps")
    out = _dollars({
        "metric": f"{name} spec-decode tok/s (bs={batch}, k=4, ngram, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "tokens/sec",
        "vs_baseline": round(val / base_v, 3) if base_v else 1.0,
    })
    out["acceptance_rate"] = round(eng.spec.acceptance_rate, 4)
    out["tokens_per_verify"] = round(eng.spec.tokens_per_verify, 4)
    out["spec_fallback_steps"] = eng.spec.fallback_steps
    out["phases"] = _phases_of(fins)  # last measured batch, warm steady-state
    return out


def bench_vllm(tiny: bool) -> dict:
    """Continuous-batching engine decode tok/s, async pipeline ON vs OFF.

    The PR-6 tentpole's measured number: the same paged-engine decode
    workload run twice — ``SHAI_ASYNC_DECODE=1`` (device-resident batch
    state + one-step-lookahead dispatch) and ``=0`` (the lock-step
    reference oracle) — in one line, so a BENCH_*.json row shows both the
    absolute tok/s and the realized pipelining speedup. The per-mode
    ``step_gap_mean_ms`` (obs.steploop ``shai_engine_step_gap_seconds``)
    says WHERE the win came from: the async path's inter-step host gap
    collapses to ~0 while lock-step pays marshal+readback every step.

    Tracing overhead note (PR 18, fleet tracing): this bench drives the
    engine directly, and the engine hot path holds NO tracing calls —
    trace attribution rides plain dict stamps on the request
    (``Request.obs_extra``), spans are grafted by the serving layer
    after the fact, and with ``SHAI_TRACE=0`` every serving-layer seam
    is the shared no-op. Measured on this cpu-tiny geometry (bs=4):
    2089.7 tok/s tracing-on vs 2217.0 tok/s tracing-off — a gap within
    this config's run-to-run variance, consistent with the
    no-engine-cost design (the deviceless overhead-guard test in
    tests/test_trace_fleet.py pins the no-op contract itself).
    """
    import os

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=128, max_num_seqs=4, block_size=8,
                            context_encoding_buckets=(32,),
                            max_new_tokens=48)
        batch, prompt_len, new = 4, 24, 48
        name = "vllm-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=8,
                            block_size=16, context_encoding_buckets=(128,),
                            max_new_tokens=128)
        batch, prompt_len, new = 8, 128, 128
        name = "vllm-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, cfg.vocab_size, prompt_len).tolist()
               for _ in range(batch)]
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    def measure(async_on: bool):
        os.environ["SHAI_ASYNC_DECODE"] = "1" if async_on else "0"
        try:
            eng = LLMEngine(cfg, params, ecfg)
        finally:
            os.environ.pop("SHAI_ASYNC_DECODE", None)

        def run():
            fins = eng.generate(prompts, sp)
            assert len(fins) == batch
            assert all(len(f.token_ids) == new for f in fins)
            return fins

        run()   # warm: prefill + decode executables
        runs = 3
        fins = []
        t0 = time.perf_counter()
        for _ in range(runs):
            fins = run()
        dt = (time.perf_counter() - t0) / runs
        gap = eng.obs.step_gap.snapshot()
        return {
            "tok_s": round(batch * new / dt, 2),
            "step_gap_mean_ms": (round(gap["sum"] / gap["count"] * 1e3, 4)
                                 if gap["count"] else 0.0),
            "pipeline_flushes": eng.obs.pipeline_flushes,
            "phases": _phases_of(fins),
        }

    on = measure(True)
    off = measure(False)
    base = _published("vllm_decode_tok_s")
    out = _dollars({
        "metric": f"{name} engine decode tok/s (bs={batch}, "
                  f"SHAI_ASYNC_DECODE on vs off, "
                  f"{jax.devices()[0].platform})",
        "value": on["tok_s"],
        "unit": "tokens/sec",
        "vs_baseline": round(on["tok_s"] / base, 3) if base else 1.0,
    })
    out["async"] = on
    out["lockstep"] = off
    out["async_speedup"] = (round(on["tok_s"] / off["tok_s"], 3)
                            if off["tok_s"] else 0.0)
    out["phases"] = on["phases"]
    return out


def bench_kvtier(tiny: bool) -> dict:
    """KV-tier warm-hit TTFT: prompt replay after eviction pressure.

    The PR-10 tentpole's measured number. One engine with the host tier ON
    (``SHAI_KVTIER=1``, synchronous copies so the measurement is
    deterministic) and a pool small enough that filler prompts evict the
    probe prompt's prefix blocks — demoting them to the host tier. Each
    round then measures (a) a COLD same-length prompt (full prefill) and
    (b) the probe REPLAY, whose prefix swaps back in via the tier's
    scatter-write restore instead of re-running prefill. ``value`` is the
    cold/warm TTFT ratio (>1 = the tier is saving prefill work); the line
    carries the tier's own counters so a regression says whether the hit
    path or the copy path moved.
    """
    import os
    import statistics

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=256, max_num_seqs=1, block_size=8,
                            num_blocks=26,
                            context_encoding_buckets=(32, 64, 128),
                            max_new_tokens=16, enable_prefix_caching=True)
        prompt_len, new = 120, 8
        name = "kvtier-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=2,
                            block_size=16, num_blocks=72,
                            context_encoding_buckets=(128, 256, 512),
                            max_new_tokens=16, enable_prefix_caching=True)
        prompt_len, new = 480, 8
        name = "kvtier-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    rng = np.random.default_rng(7)
    probe = rng.integers(3, cfg.vocab_size, prompt_len).tolist()
    fillers = [rng.integers(3, cfg.vocab_size, prompt_len).tolist()
               for _ in range(3)]
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    os.environ["SHAI_KVTIER"] = "1"
    os.environ["SHAI_KVTIER_ASYNC"] = "0"  # deterministic copy timing
    try:
        eng = LLMEngine(cfg, params, ecfg)
    finally:
        os.environ.pop("SHAI_KVTIER", None)
        os.environ.pop("SHAI_KVTIER_ASYNC", None)
    assert eng.cache.tier is not None

    def ttft_of(prompt):
        [fin] = eng.generate([list(prompt)], sp)
        return fin.timing["prefill_s"]

    # warm every executable on the path (prefill buckets, cont chunks,
    # decode, tier movers) before timing anything
    ttft_of(probe)
    for f in fillers:
        ttft_of(f)
    ttft_of(probe)

    colds, warms = [], []
    for r in range(3):
        for f in fillers:  # eviction pressure: the probe's blocks demote
            ttft_of(f)
        cold = list(probe)
        cold[0] = int(cold[0]) % (cfg.vocab_size - 4) + 3 + r + 1
        colds.append(ttft_of(cold))      # same length, cold first block
        warms.append(ttft_of(probe))     # host-tier restore path
    cold_p50 = statistics.median(colds)
    warm_p50 = statistics.median(warms)
    snap = eng.cache.tier.snapshot()
    base = _published("kvtier_warm_ttft_speedup")
    val = round(cold_p50 / warm_p50, 3) if warm_p50 else 0.0
    return {
        "metric": f"{name} warm-host-tier TTFT speedup (prompt "
                  f"{prompt_len}, replay after eviction, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "x",
        "vs_baseline": round(val / base, 3) if base else 1.0,
        "cold_ttft_ms": round(cold_p50 * 1e3, 3),
        "warm_ttft_ms": round(warm_p50 * 1e3, 3),
        "tier": {k: snap[k] for k in ("hits", "misses", "stores",
                                      "restored", "evictions", "errors")},
    }


def bench_ragged(tiny: bool) -> dict:
    """Ragged paged attention + int8 KV A/B: one mixed-length decode
    workload measured with ``SHAI_RAGGED_ATTENTION=1 SHAI_KV_QUANT=int8``
    vs both off (the bucketed bf16 oracle).

    Reports tok/s at MIXED prompt lengths (the case the bucket ladder
    padded on), the pad fraction each mode dispatched, the decode
    executable-ladder entry count (ragged collapses the
    ``token_generation_buckets`` grid to one context entry), and
    ``kv_quant_capacity_ratio``: how many KV blocks each pool dtype fits
    at a fixed ``SHAI_HBM_GIB`` (params + activations priced by
    ``core.budget.causal_lm_budget``, per-block bytes measured from the
    LIVE pools — scales included) — the ~2x batch headroom per HBM byte
    the int8 pool buys.
    """
    import os

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.core.budget import (
        GIB,
        causal_lm_budget,
    )
    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=256, max_num_seqs=4, block_size=8,
                            context_encoding_buckets=(32, 64, 128),
                            token_generation_buckets=(64, 128),
                            max_new_tokens=16)
        lens, new = (12, 40, 90, 120), 12
        name = "ragged-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=4,
                            block_size=16,
                            context_encoding_buckets=(128, 256, 512),
                            token_generation_buckets=(256, 512),
                            max_new_tokens=32)
        lens, new = (60, 200, 450, 700), 24
        name = "ragged-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(3, cfg.vocab_size, n).tolist() for n in lens]
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    def measure(ragged_quant: bool):
        env = ({"SHAI_RAGGED_ATTENTION": "1", "SHAI_KV_QUANT": "int8"}
               if ragged_quant else
               {"SHAI_RAGGED_ATTENTION": "0", "SHAI_KV_QUANT": "off"})
        os.environ.update(env)
        try:
            eng = LLMEngine(cfg, params, ecfg)
        finally:
            for k in env:
                os.environ.pop(k, None)

        def run():
            fins = eng.generate(prompts, sp)
            assert all(len(f.token_ids) == new for f in fins)

        run()   # warm every executable on the mixed-length path
        runs = 3
        t0 = time.perf_counter()
        for _ in range(runs):
            run()
        dt = (time.perf_counter() - t0) / runs
        snap = eng.obs.snapshot()
        return {
            "tok_s": round(len(prompts) * new / dt, 2),
            "pad_fraction": snap["pad_fraction"],
            "decode_ladder_entries": len(eng._decode_fns),
            "executables": eng.n_executables,
            "kv_pool_bytes": eng.cache.pool_bytes,
            "kv_pool_blocks": eng.cache.total_blocks,
        }

    on = measure(True)
    off = measure(False)

    # capacity math at a pinned HBM size: blocks each pool dtype fits once
    # params + peak activations are carved out (per-block bytes measured
    # from the live pools above, scale arrays included)
    from scalable_hw_agnostic_inference_tpu.obs.util import env_float

    hbm_gib = env_float("SHAI_HBM_GIB", 16.0)
    budget = causal_lm_budget(cfg, ecfg, hbm_gib_per_chip=hbm_gib)
    kv_budget = max(0.0, (budget.usable_gib - budget.params_gib
                          - budget.act_gib)) * GIB
    blk_off = off["kv_pool_bytes"] / off["kv_pool_blocks"]
    blk_on = on["kv_pool_bytes"] / on["kv_pool_blocks"]
    max_blocks_off = int(kv_budget // blk_off)
    max_blocks_on = int(kv_budget // blk_on)
    ratio = (round(max_blocks_on / max_blocks_off, 3)
             if max_blocks_off else 0.0)

    base = _published("ragged_tps")
    out = _dollars({
        "metric": f"{name} ragged+int8KV decode tok/s (mixed lens "
                  f"{list(lens)}, vs bucketed bf16, "
                  f"{jax.devices()[0].platform})",
        "value": on["tok_s"],
        "unit": "tokens/sec",
        "vs_baseline": round(on["tok_s"] / base, 3) if base else 1.0,
    })
    out["ragged_quant"] = on
    out["bucketed"] = off
    out["speedup"] = (round(on["tok_s"] / off["tok_s"], 3)
                      if off["tok_s"] else 0.0)
    out["kv_quant_capacity_ratio"] = ratio
    out["max_kv_blocks_at_hbm"] = {"hbm_gib": hbm_gib,
                                   "bf16": max_blocks_off,
                                   "int8": max_blocks_on}
    return out


def bench_fused(tiny: bool) -> dict:
    """Fused mixed-phase step A/B: one mixed prefill/decode workload
    measured with ``SHAI_FUSED_STEP=1`` (decode rows + the continuation
    chunk window in ONE ragged dispatch per step) vs the laddered ragged
    engine (separate decode and continuation executables, serialized
    dispatches). Ragged + async decode are ON in both modes — the A/B
    isolates the fusion.

    The workload is the interference case the fusion targets: a second
    wave of prompts (one long enough to chunk) joins mid-decode, so the
    laddered engine pays a separate continuation dispatch between decode
    steps while the fused engine rides the chunk on the SAME dispatch.
    Reports per-mode TTFT/TPOT medians, the decode-side ladder entry
    count (fused collapses decode+rcont to one entry per batch bucket),
    warmup wall time, and ``fused_step_tpot_ratio`` (laddered TPOT /
    fused TPOT — above 1.0 means the fusion pays).
    """
    import os
    import statistics

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=256, max_num_seqs=4, block_size=8,
                            context_encoding_buckets=(32, 64, 128),
                            token_generation_buckets=(64, 128),
                            max_new_tokens=16)
        wave1, wave2, new = (12, 40, 90), (140, 20), 12
        name = "fused-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=4,
                            block_size=16,
                            context_encoding_buckets=(128, 256, 512),
                            token_generation_buckets=(256, 512),
                            max_new_tokens=32)
        wave1, wave2, new = (60, 200, 450), (700, 100), 24
        name = "fused-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    rng = np.random.default_rng(17)
    p1 = [rng.integers(3, cfg.vocab_size, n).tolist() for n in wave1]
    p2 = [rng.integers(3, cfg.vocab_size, n).tolist() for n in wave2]
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    def run_mixed(eng):
        """Two-wave mixed load: wave 2 (chunked long prompt included)
        joins after wave 1 started decoding. Returns Finished in
        submission order."""
        fins = {}
        rids = [eng.add_request(p, sp) for p in p1]
        steps = 0
        while len(fins) < len(p1) + len(p2):
            for f in eng.step():
                fins[f.req_id] = f
            steps += 1
            if steps == 2:
                rids += [eng.add_request(p, sp) for p in p2]
        return [fins[r] for r in rids]

    def measure(fused: bool):
        env = {"SHAI_RAGGED_ATTENTION": "1", "SHAI_ASYNC_DECODE": "1",
               "SHAI_FUSED_STEP": "1" if fused else "0"}
        os.environ.update(env)
        try:
            eng = LLMEngine(cfg, params, ecfg)
        finally:
            for k in env:
                os.environ.pop(k, None)
        t0 = time.perf_counter()
        eng.warm_executables()
        warm_s = time.perf_counter() - t0
        run_mixed(eng)  # shake out host-side laziness off the clock
        runs = 3
        ttfts, tpots, errors = [], [], 0
        t0 = time.perf_counter()
        for _ in range(runs):
            for f in run_mixed(eng):
                if f.stop_reason != "length" or len(f.token_ids) != new:
                    errors += 1
                    continue
                t = f.timing or {}
                ttfts.append(t.get("queue_s", 0.0) + t.get("prefill_s", 0.0))
                tpots.append(t.get("decode_s", 0.0) / max(1, new - 1))
        dt = (time.perf_counter() - t0) / runs
        n_prompts = len(p1) + len(p2)
        # decode-side ladder: the per-step dispatch executables — fused
        # entries replace BOTH the (ctx, batch) decode grid and the
        # ragged continuation ladder
        ladder = (len(eng._fused_fns) if fused else
                  len(eng._decode_fns)
                  + sum(1 for k in eng._prefill if k[0] == "rcont"))
        return {
            "tok_s": round(n_prompts * new / dt, 2),
            "ttft_s_p50": round(statistics.median(ttfts), 4),
            "tpot_s_p50": round(statistics.median(tpots), 5),
            "decode_ladder_entries": ladder,
            "executables": eng.n_executables,
            "warmup_s": round(warm_s, 2),
            "errors": errors,
        }

    on = measure(True)
    off = measure(False)
    ratio = (round(off["tpot_s_p50"] / on["tpot_s_p50"], 3)
             if on["tpot_s_p50"] else 0.0)

    base = _published("fused_step_tpot_ratio")
    out = {
        "metric": f"{name} fused mixed-phase step TPOT ratio (laddered/"
                  f"fused, mixed 2-wave load, {jax.devices()[0].platform})",
        "value": ratio,
        "unit": "x",
        "vs_baseline": round(ratio / base, 3) if base else 1.0,
        "fused_step_tpot_ratio": ratio,
        "fused": on,
        "laddered": off,
        "ttft_improvement": (round(off["ttft_s_p50"] / on["ttft_s_p50"], 3)
                             if on["ttft_s_p50"] else 0.0),
        "ladder_entries_reduced": (on["decode_ladder_entries"]
                                   < off["decode_ladder_entries"]),
    }
    return out


def bench_qos(tiny: bool) -> dict:
    """Multi-tenant QoS A/B: high-priority tenant p99 TTFT under a
    low-priority flood, ``SHAI_QOS=1`` (weighted-fair dequeue + priority
    preemption) vs ``=0`` (FIFO).

    One engine per mode runs identical seeded rounds: the flood tenant
    parks a burst of low-priority requests in the queue, then the vip
    tenant submits ONE high-priority request; the measurement is the vip
    request's realized TTFT (t_first - t_submit from the obs timeline).
    ``value`` is ``qos_flood_p99_ratio`` = FIFO flooded p99 / QoS flooded
    p99 — how many × of the flood-induced TTFT inflation the class-aware
    dequeue removes (>1 = QoS is protecting the high class). The line
    carries both modes' p50/p99 plus the no-flood baseline so a
    regression says whether QoS got worse or the flood got cheaper.
    """
    import os
    import statistics

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        ecfg = EngineConfig(max_model_len=128, max_num_seqs=2, block_size=8,
                            context_encoding_buckets=(32,),
                            max_new_tokens=24)
        n_flood, flood_new, vip_new, rounds = 6, 16, 4, 6
        prompt_len = 20
        name = "qos-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        ecfg = EngineConfig(max_model_len=1024, max_num_seqs=4,
                            block_size=16, context_encoding_buckets=(128,),
                            max_new_tokens=96)
        n_flood, flood_new, vip_new, rounds = 12, 64, 16, 5
        prompt_len = 100
        name = "qos-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)

    def measure(qos_on: bool):
        os.environ["SHAI_QOS"] = "1" if qos_on else "0"
        try:
            eng = LLMEngine(cfg, params, ecfg)
        finally:
            os.environ.pop("SHAI_QOS", None)
        rng = np.random.default_rng(17)  # same schedule both modes
        sp_flood = SamplingParams(temperature=0.0,
                                  max_new_tokens=flood_new)
        sp_vip = SamplingParams(temperature=0.0, max_new_tokens=vip_new)

        def prompt():
            return rng.integers(3, cfg.vocab_size, prompt_len).tolist()

        def drain(ids):
            done = {}
            while set(ids) - set(done):
                for f in eng.step():
                    done[f.req_id] = f
            return done

        drain([eng.add_request(prompt(), sp_vip)])  # warm the ladder
        # no-flood baseline: the vip tenant alone
        base = []
        for _ in range(rounds):
            rid = eng.add_request(prompt(), sp_vip, priority=0,
                                  tenant="vip")
            fin = drain([rid])[rid]
            base.append(fin.timing["t_first"] - fin.timing["t_submit"])
        # flooded rounds: the flood queues first, vip arrives last
        vip = []
        for _ in range(rounds):
            flood = [eng.add_request(prompt(), sp_flood, priority=2,
                                     tenant="flood")
                     for _ in range(n_flood)]
            eng.step()  # the flood takes the slots/queue
            rid = eng.add_request(prompt(), sp_vip, priority=0,
                                  tenant="vip")
            done = drain(flood + [rid])
            fin = done[rid]
            vip.append(fin.timing["t_first"] - fin.timing["t_submit"])

        return {
            "vip_ttft_p50_ms": round(statistics.median(vip) * 1e3, 2),
            "vip_ttft_p99_ms": round(_pctl(vip, 0.99) * 1e3, 2),
            "vip_ttft_noflood_p50_ms": round(
                statistics.median(base) * 1e3, 2),
            "preemptions": eng.obs.preemptions,
        }

    on = measure(True)
    off = measure(False)
    base = _published("qos_flood_p99_ratio")
    val = (round(off["vip_ttft_p99_ms"] / on["vip_ttft_p99_ms"], 3)
           if on["vip_ttft_p99_ms"] else 0.0)
    return {
        "metric": f"{name} high-priority p99 TTFT under low-priority "
                  f"flood, FIFO/QoS ratio ({n_flood}-deep flood, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "x",
        "vs_baseline": round(val / base, 3) if base else 1.0,
        "qos": on,
        "fifo": off,
    }


def bench_disagg(tiny: bool) -> dict:
    """Disaggregated prefill/decode A/B: a two-engine prefill/decode split
    (warm KV shipped through the kvnet frame codec, the in-process stand-in
    for the socket hop) vs one monolithic engine, under a mixed-length
    prompt load.

    Each round submits a fresh batch of mixed-length prompts concurrently.
    The monolithic engine pays every prompt's full prefill inline with its
    decoding batch; the decode engine receives each round's KV runs the
    way a handoff delivers them — prefill engine (role=prefill) finishes
    the prompt, its tier's run crosses ``encode_frames``/``decode_frames``
    byte-exact into the decode engine's host tier — and admits via the
    tier restore. ``value`` is ``disagg_ttft_ratio`` = mono TTFT p50 /
    disagg TTFT p50 on the decode side (>1 = the split is buying TTFT);
    the line carries p50/p99 TTFT + TPOT p50 for both modes so a
    regression says whether the restore path or the decode pace moved.
    Network latency is NOT modeled — the line measures the compute-side
    win of restoring vs re-prefilling, the same quantity the live socket
    test exercises end-to-end.
    """
    import os
    import statistics

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.kvnet import frames
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    # the load is LONG mixed-length prompts — past the largest prefill
    # bucket, so the monolithic pod pays the chunked-prefill ladder
    # serially inside its decoding batch (THE TTFT/TPOT interference the
    # split exists to remove), while the decode pod restores the banked
    # run and computes only the tail chunk
    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        kw = dict(max_model_len=256, max_num_seqs=4, block_size=8,
                  context_encoding_buckets=(32, 64, 128),
                  max_new_tokens=16, enable_prefix_caching=True)
        lens, new, rounds = (240, 192, 160, 232), 8, 3
        name = "disagg-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        kw = dict(max_model_len=1024, max_num_seqs=4, block_size=16,
                  context_encoding_buckets=(128, 256, 512),
                  max_new_tokens=32, enable_prefix_caching=True)
        lens, new, rounds = (960, 832, 704, 928), 16, 3
        name = "disagg-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)
    sp1 = SamplingParams(temperature=0.0, max_new_tokens=1)

    def build(role: str, tier: bool) -> LLMEngine:
        os.environ["SHAI_KVTIER"] = "1" if tier else "0"
        os.environ["SHAI_KVTIER_ASYNC"] = "0"  # deterministic copies
        try:
            return LLMEngine(cfg, params, EngineConfig(role=role, **kw))
        finally:
            os.environ.pop("SHAI_KVTIER", None)
            os.environ.pop("SHAI_KVTIER_ASYNC", None)

    def prompts_for(round_i: int):
        rng = np.random.default_rng(31 + round_i)  # fresh every round:
        return [rng.integers(3, cfg.vocab_size, n).tolist()  # no device-
                for n in lens]                               # cache reuse

    def run_batch(eng, batch, params_):
        ids = [eng.add_request(list(p), params_) for p in batch]
        done = {}
        while set(ids) - set(done):
            for f in eng.step():
                done[f.req_id] = f
        eng.finish_pending()
        return [done[i] for i in ids]

    def ttfts(fins):
        return [f.timing["t_first"] - f.timing["t_submit"] for f in fins]

    def tpots(fins):
        return [f.timing["decode_s"] / max(1, len(f.token_ids) - 1)
                for f in fins if f.timing and "decode_s" in f.timing]

    def ship(pre: LLMEngine, dec: LLMEngine, batch) -> int:
        """The handoff wire, in-process: prefill tier run -> frames ->
        decode tier (byte-exact, same as GET /kv/blocks)."""
        moved = 0
        for p in batch:
            hashes = pre.cache.prefix_hashes(list(p))
            run = pre.cache.tier.get_run(hashes)
            if not run:
                continue
            entries = frames.decode_frames(frames.encode_frames(run))
            n_arr = len(entries[0]) - 1
            stacked = [np.stack([e[1 + ai] for e in entries], axis=1)
                       for ai in range(n_arr)]
            dec.cache.tier.store_batch([e[0] for e in entries], *stacked,
                                       len(entries))
            moved += len(entries)
        return moved

    # monolithic oracle: full prefill inline with the decode batch
    mono = LLMEngine(cfg, params, EngineConfig(**kw))
    run_batch(mono, prompts_for(99), sp)  # warm every executable
    mono_fins = []
    for r in range(rounds):
        mono_fins += run_batch(mono, prompts_for(r), sp)

    # split: prefill engine banks KV, decode engine restores + generates
    pre = build("prefill", tier=True)
    dec = build("decode", tier=True)
    warm = prompts_for(99)
    run_batch(pre, warm, sp1)
    ship(pre, dec, warm)
    run_batch(dec, warm, sp)              # warm incl. the restore movers
    dec_fins, shipped = [], 0
    for r in range(rounds):
        batch = prompts_for(r)
        run_batch(pre, batch, sp1)        # the prefill tier's work
        shipped += ship(pre, dec, batch)  # the wire
        dec_fins += run_batch(dec, batch, sp)  # the decode tier's TTFT

    mono_ttft, dec_ttft = ttfts(mono_fins), ttfts(dec_fins)
    val = (round(statistics.median(mono_ttft)
                 / statistics.median(dec_ttft), 3)
           if statistics.median(dec_ttft) else 0.0)
    base = _published("disagg_ttft_ratio")
    snap = dec.cache.tier.snapshot()
    return {
        "metric": f"{name} decode-pod TTFT vs monolithic under mixed "
                  f"prompt load, p50 ratio (batch {len(lens)}, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "x",
        "vs_baseline": round(val / base, 3) if base else 1.0,
        "mono_ttft_p50_ms": round(statistics.median(mono_ttft) * 1e3, 3),
        "mono_ttft_p99_ms": round(_pctl(mono_ttft, 0.99) * 1e3, 3),
        "disagg_ttft_p50_ms": round(statistics.median(dec_ttft) * 1e3, 3),
        "disagg_ttft_p99_ms": round(_pctl(dec_ttft, 0.99) * 1e3, 3),
        "mono_tpot_p50_ms": round(
            statistics.median(tpots(mono_fins)) * 1e3, 3),
        "disagg_tpot_p50_ms": round(
            statistics.median(tpots(dec_fins)) * 1e3, 3),
        "blocks_shipped": shipped,
        "decode_tier": {k: snap[k] for k in ("stores", "restored",
                                             "evictions", "errors")},
    }


def bench_kvfabric(tiny: bool) -> dict:
    """KV fabric A/B: peer-probe admission vs cold recompute under a
    shared-system-prompt workload.

    Pod A (role=prefill, host tier on) prefills each round's prompts and
    banks their KV runs; pod B runs the same round twice as two fresh
    engines — fabric OFF (every round's new system prefix is a full
    prefill) and fabric ON with a pushed-down holder slice naming pod A
    (the probe rung pulls the run over the kvnet wire — an
    ``httpx.MockTransport`` wired to pod A's tier through the REAL
    ``KvNetClient`` fetch/validate/publish path — and ordinary warm
    admission restores it). ``value`` is ``kvfabric_warm_ttft_ratio`` =
    fabric-off TTFT p50 / fabric-on TTFT p50 (>1 = the fabric is buying
    TTFT). Greedy decode on both sides; the line asserts token-exactness
    in-line and REQUIRES zero transport errors — a ratio produced by a
    degraded run is a lie, not a measurement. Network latency is NOT
    modeled (same caveat as bench_disagg): this is the compute-side win
    of restoring vs re-prefilling; the live two-pod socket test covers
    the wire end-to-end."""
    import os
    import statistics

    import httpx
    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.kvnet import frames
    from scalable_hw_agnostic_inference_tpu.kvnet.client import KvNetClient
    from scalable_hw_agnostic_inference_tpu.kvnet.directory import (
        FabricProbe,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        kw = dict(max_model_len=768, max_num_seqs=4, block_size=8,
                  context_encoding_buckets=(32, 64, 128, 256),
                  max_new_tokens=16, enable_prefix_caching=True)
        n_prefix, n_tail, batch, new, rounds = 576, 24, 4, 8, 3
        name = "kvfabric-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        kw = dict(max_model_len=1024, max_num_seqs=4, block_size=16,
                  context_encoding_buckets=(128, 256, 512),
                  max_new_tokens=32, enable_prefix_caching=True)
        n_prefix, n_tail, batch, new, rounds = 768, 64, 4, 16, 3
        name = "kvfabric-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)
    sp1 = SamplingParams(temperature=0.0, max_new_tokens=1)
    peer = "http://pod-a"

    def build(role: str = "both") -> LLMEngine:
        os.environ["SHAI_KVTIER"] = "1"
        os.environ["SHAI_KVTIER_ASYNC"] = "0"  # deterministic copies
        try:
            return LLMEngine(cfg, params, EngineConfig(role=role, **kw))
        finally:
            os.environ.pop("SHAI_KVTIER", None)
            os.environ.pop("SHAI_KVTIER_ASYNC", None)

    def prompts_for(round_i: int):
        # ONE shared system prefix per round (fresh each round: no
        # device-cache reuse across rounds), distinct per-request tails
        rng = np.random.default_rng(47 + round_i)
        prefix = rng.integers(3, cfg.vocab_size, n_prefix).tolist()
        return [prefix + rng.integers(3, cfg.vocab_size, n_tail).tolist()
                for _ in range(batch)]

    def run_batch(eng, prompts, params_, holders=None):
        ids = [eng.add_request(list(p), params_, kv_holders=holders)
               for p in prompts]
        done = {}
        while set(ids) - set(done):
            for f in eng.step():
                done[f.req_id] = f
        eng.finish_pending()
        return [done[i] for i in ids]

    def ttfts(fins):
        return [f.timing["t_first"] - f.timing["t_submit"] for f in fins]

    # pod A: banks every round's runs in its host tier (the holder)
    pod_a = build("prefill")
    run_batch(pod_a, prompts_for(99), sp1)          # warm executables
    tier_a = pod_a.cache.tier

    def handler(request: "httpx.Request") -> "httpx.Response":
        # pod A's /kv/blocks, served in-process: same frames, same
        # leading-run contract the socket endpoint implements
        if request.url.path == "/kv/blocks":
            hs = [int(h) for h in
                  (request.url.params.get("hashes") or "").split(",") if h]
            run = tier_a.get_run(hs)
            return httpx.Response(200, content=frames.encode_frames(run))
        return httpx.Response(404)

    def arm(eng: LLMEngine) -> FabricProbe:
        fab = FabricProbe(
            eng.cache.tier, kvnet_stats=eng.obs.kvnet, peers=[],
            client=KvNetClient(eng.cache.tier, eng.obs.kvnet,
                               transport=httpx.MockTransport(handler)))
        eng._kvfabric = fab
        eng.obs.kvfabric = fab.stats
        return fab

    b_off = build()
    b_on = build()
    fab = arm(b_on)
    # warm both B engines' executables on an unrelated round (and pod A
    # banks it so the fabric-on warm-up walks the full probe+restore
    # path — the restore movers compile OUTSIDE the measured rounds)
    warm = prompts_for(98)
    run_batch(pod_a, warm, sp1)
    run_batch(b_off, warm, sp)
    run_batch(b_on, warm, sp, holders=[peer])

    off_fins, on_fins = [], []
    for r in range(rounds):
        prompts = prompts_for(r)
        run_batch(pod_a, prompts, sp1)              # the holder's banking
        off_fins += run_batch(b_off, prompts, sp)   # cold: full prefill
        on_fins += run_batch(b_on, prompts, sp,     # warm: probe+restore
                             holders=[peer])

    # token-exactness is part of the measurement's validity, not a
    # separate test: greedy fabric-on output must equal fabric-off
    for fo, fn in zip(off_fins, on_fins):
        assert list(fo.token_ids) == list(fn.token_ids), \
            "kvfabric changed greedy tokens — the ratio is invalid"
    kv_errors = int(b_on.obs.kvnet.snapshot()["errors"])
    assert kv_errors == 0, f"kvfabric bench saw {kv_errors} kvnet errors"
    fsnap = fab.stats.snapshot()
    assert fsnap["remote_hits"] > 0, "fabric probe never landed a run"

    off_ttft, on_ttft = ttfts(off_fins), ttfts(on_fins)
    val = (round(statistics.median(off_ttft)
                 / statistics.median(on_ttft), 3)
           if statistics.median(on_ttft) else 0.0)
    base = _published("kvfabric_warm_ttft_ratio")
    return {
        "metric": f"{name} shared-system-prompt TTFT, fabric-off vs "
                  f"fabric-on p50 ratio (batch {batch}, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "x",
        "vs_baseline": round(val / base, 3) if base else 1.0,
        "off_ttft_p50_ms": round(statistics.median(off_ttft) * 1e3, 3),
        "off_ttft_p99_ms": round(_pctl(off_ttft, 0.99) * 1e3, 3),
        "on_ttft_p50_ms": round(statistics.median(on_ttft) * 1e3, 3),
        "on_ttft_p99_ms": round(_pctl(on_ttft, 0.99) * 1e3, 3),
        "errors": kv_errors,
        "kvfabric": {k: fsnap[k] for k in ("probes", "remote_hits",
                                           "remote_misses",
                                           "stale_holders")},
    }


def bench_migrate(tiny: bool) -> dict:
    """Live migration A/B: drain-with-migration vs drain-with-recompute
    under a mid-decode drain cut (the in-process stand-in for a
    mid-stream SIGTERM — the engines' migrate/resume path IS the one the
    socket drain drives).

    Each round decodes a batch on pod A, cuts it mid-decode (the drain's
    migrate sweep: ``migrate_out`` snapshots + banks KV), and resumes
    every request on pod B. The **migrate** arm ships the banked KV run
    through the MIGRATE envelope codec (byte-exact, same as
    ``POST /kv/migrate``) so B restores instead of re-prefilling; the
    **recompute** arm ships the manifest only (the drain-without-
    migration world: the replay pays full prefill over prompt+generated).
    ``value`` is ``migrate_resume_p50_ms`` — the migrated arm's p50
    added latency from the drain CUT to each resumed request's next
    token (snapshot + envelope + publish + restore-vs-reprefill: the
    whole stall a client sees; the decode tail past it is identical in
    both arms) — and the line carries the recompute arm's p50, the
    recompute/migrate ratio (>1 = migration is buying resume latency),
    and the REQUIRED ``errors`` count (0: every cut request completes,
    token-exact vs an uninterrupted oracle — the ladder's no-failure
    contract, measured).
    """
    import os
    import statistics
    import time as _time

    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.kvnet import migrate as migmod
    from scalable_hw_agnostic_inference_tpu.kvnet.client import publish_run
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig.tiny()
        kw = dict(max_model_len=256, max_num_seqs=4, block_size=8,
                  context_encoding_buckets=(32, 64, 128),
                  max_new_tokens=64, enable_prefix_caching=True)
        # LONG prompts: the resume's cost split is restore-vs-reprefill,
        # so the arm gap is the prompt's prefill cost (the same quantity
        # bench_kvtier's warm-replay line measures)
        lens, new, cut_steps, rounds = (240, 192, 160, 232), 12, 14, 3
        name = "migrate-tiny"
    else:
        cfg = llama_mod.LlamaConfig.llama32_1b()
        kw = dict(max_model_len=1024, max_num_seqs=4, block_size=16,
                  context_encoding_buckets=(128, 256, 512),
                  max_new_tokens=64, enable_prefix_caching=True)
        lens, new, cut_steps, rounds = (960, 832, 704, 928), 24, 18, 3
        name = "migrate-1b-geometry"

    params = llama_mod.geometry_params(cfg, quant=False)
    sp = SamplingParams(temperature=0.0, max_new_tokens=new)

    def build() -> LLMEngine:
        os.environ["SHAI_KVTIER"] = "1"
        os.environ["SHAI_KVTIER_ASYNC"] = "0"  # deterministic copies
        try:
            return LLMEngine(cfg, params, EngineConfig(**kw))
        finally:
            os.environ.pop("SHAI_KVTIER", None)
            os.environ.pop("SHAI_KVTIER_ASYNC", None)

    def prompts_for(round_i: int):
        rng = np.random.default_rng(47 + round_i)
        return [rng.integers(3, cfg.vocab_size, n).tolist() for n in lens]

    def run_batch(eng, batch, params_):
        ids = [eng.add_request(list(p), params_) for p in batch]
        done = {}
        while set(ids) - set(done):
            for f in eng.step():
                done[f.req_id] = f
        eng.finish_pending()
        return [done[i] for i in ids]

    def drain_to_done(eng, done):
        while eng.has_work:
            for f in eng.step():
                done[f.req_id] = (f, _time.monotonic())
        eng.finish_pending()

    # the uninterrupted oracle outputs, per round (token-exactness is an
    # ACCEPTANCE condition of this line, not just a latency number)
    oracle = build()
    run_batch(oracle, prompts_for(99), sp)  # warm every executable
    want = {r: [f.token_ids for f in run_batch(oracle, prompts_for(r), sp)]
            for r in range(rounds)}

    def arm(ship_kv: bool):
        A, B = build(), build()
        run_batch(A, prompts_for(99), sp)   # warm both pods' ladders
        run_batch(B, prompts_for(99), sp)
        lat, shipped, errors = [], 0, 0
        # one UNMEASURED cut+resume cycle first: the resume's warm
        # admission dispatches continuation executables at (start,
        # bucket) keys the plain warm batch never reaches — their
        # first-use compiles are warmup, not resume latency
        for r in [98] + list(range(rounds)):
            measured = r != 98
            batch = prompts_for(r)
            rids = [A.add_request(list(p), sp) for p in batch]
            early = {}
            for _ in range(cut_steps):     # mid-decode: the drain cut
                for f in A.step():
                    early[f.req_id] = f
            t_cut = _time.monotonic()
            resumes = []
            for i, rid in enumerate(rids):
                if rid in early:           # finished before the cut
                    continue
                fin = A.migrate_out(rid)
                if fin is None or fin.stop_reason != "migrated":
                    continue               # pending token completed it
                man = fin.migration
                entries = (A.cache.tier.get_run(man["hashes"])
                           if ship_kv and man["hashes"] else [])
                # the wire: envelope encode/decode, byte-exact
                man2, ent2 = migmod.decode_migration(
                    migmod.encode_migration(man, entries))
                if ent2:
                    shipped += publish_run(
                        B.cache.tier, [int(h) for h in man2["hashes"]],
                        ent2)
                pr = man2["params"]
                sp2 = SamplingParams(
                    temperature=pr["temperature"], top_k=pr["top_k"],
                    top_p=pr["top_p"],
                    max_new_tokens=pr["max_new_tokens"],
                    eos_id=pr["eos_id"])
                rid2 = B.add_request(
                    man2["prompt_ids"], sp2,
                    already_generated=man2["generated"],
                    orig_n_prompt=man2["n_prompt"])
                resumes.append((rid2, i))
            A.finish_pending()
            done = {}
            drain_to_done(B, done)
            if not measured:
                continue
            for rid2, i in resumes:
                if rid2 not in done:
                    errors += 1
                    continue
                fin, t_done = done[rid2]
                del t_done
                if (fin.stop_reason not in ("length", "eos")
                        or fin.token_ids != want[r][i]):
                    errors += 1
                    continue
                # the ADDED latency a client sees: from the drain CUT to
                # the resumed stream's next token. Measured from t_cut,
                # not the resume's submit — the migrate arm's snapshot/
                # envelope/publish cost happens between the two and is
                # part of the migration bill (excluding it would bias
                # the promoted ratio toward migration); the decode tail
                # after t_first is identical in both arms and excluded.
                lat.append(max(0.0, fin.timing["t_first"] - t_cut))
        return lat, shipped, errors

    mig_lat, blocks_shipped, mig_errors = arm(ship_kv=True)
    rec_lat, _, rec_errors = arm(ship_kv=False)
    mig_p50 = statistics.median(mig_lat) * 1e3 if mig_lat else 0.0
    rec_p50 = statistics.median(rec_lat) * 1e3 if rec_lat else 0.0
    base = _published("migrate_resume_p50_ms")
    return {
        "metric": f"{name} resumed-request added latency p50 after a "
                  f"mid-decode drain cut, migrate vs recompute "
                  f"({jax.devices()[0].platform})",
        "value": round(mig_p50, 3),
        "unit": "ms",
        # latency metric: smaller is better, vs_baseline inverts
        "vs_baseline": round(base / mig_p50, 3) if base and mig_p50
        else 1.0,
        "migrate_resume_p50_ms": round(mig_p50, 3),
        "migrate_resume_p99_ms": round(_pctl(mig_lat, 0.99) * 1e3, 3)
        if mig_lat else 0.0,
        "recompute_resume_p50_ms": round(rec_p50, 3),
        "recompute_over_migrate_ratio": round(rec_p50 / mig_p50, 3)
        if mig_p50 else 0.0,
        "resumed_requests": len(mig_lat),
        "blocks_shipped": blocks_shipped,
        "errors": mig_errors + rec_errors,  # MUST be 0: the ladder's
        # no-request-failure contract, measured
    }


def bench_scaler(tiny: bool) -> dict:
    """Autoscaler control-quality line: deviceless, trace-driven.

    Two questions, two traces, one simulator
    (``orchestrate/load_sim.py``):

    * **recovery** (the promoted value): replay the flash-crowd trace
      and measure SLO-recovery time — seconds from spike onset to the
      first sustained run of SLO-compliant ticks. Smaller is better, so
      ``vs_baseline`` inverts like the migrate line.
    * **economics**: replay the diurnal trace twice — scaled fleet vs a
      static fleet sized for PEAK need — and report
      ``pod_hours_ratio`` (scaled/static, < 1 = the controller pays for
      fewer pod-hours). The comparison only counts at equal SLO
      compliance, so both runs' compliance rides the line and the
      scaled run must stay inside the trace's error budget.

    ``errors`` is REQUIRED 0 (every simulated request reaches exactly
    one terminal state), and the control invariants (herd cap, anti-flap
    spacing, migrate-storm cap, recovery window) must hold on both
    traces — a violation fails the bench, not just dents the number.
    The pod capacity/warm-up prices come from PERF_MODEL.json via
    PerfPricer, so the sim's economics share the capacity checker's
    math. ``tiny`` shortens the traces; the control law is identical.
    """
    from scalable_hw_agnostic_inference_tpu.orchestrate import load_sim

    if tiny:
        flash = load_sim.flash_crowd_trace(duration_s=2700.0)
        day = load_sim.diurnal_trace(duration_s=3600.0)
        name = "scaler-tiny"
    else:
        flash = load_sim.flash_crowd_trace()
        day = load_sim.diurnal_trace()
        name = "scaler"

    crowd = load_sim.run_fleet_sim(flash)
    viol = crowd.violations()
    assert not viol, f"flash-crowd invariants violated: {viol}"
    rec = crowd.recovery_s()
    assert rec is not None, "fleet never recovered SLO after the spike"

    dyn = load_sim.run_fleet_sim(day)
    dviol = dyn.violations()
    assert not dviol, f"diurnal invariants violated: {dviol}"
    # the static strawman: a fleet sized for the trace's PEAK need,
    # priced with the SAME capacity math the scaler uses
    sim0 = load_sim.FleetSim(day)
    peak_rps = max(day.rps_fn(i * day.tick_s)
                   for i in range(int(day.duration_s / day.tick_s)))
    peak_need = sim0.scaler.pricer.replicas_for(
        peak_rps, util=sim0.cfg.target_util) or 8
    static = load_sim.run_fleet_sim(day, static_replicas=peak_need)
    ratio = (round(dyn.pod_hours / static.pod_hours, 3)
             if static.pod_hours else 0.0)
    # equal-compliance guard: the cheaper fleet must still hold the SLO
    budget = sim0.budget_frac
    assert dyn.slo_compliance() >= 1.0 - budget, \
        f"scaled diurnal compliance {dyn.slo_compliance():.3f} blew " \
        f"the {budget:.0%} budget — the ratio would be bought with " \
        f"SLO debt"

    errors = crowd.errors + dyn.errors + static.errors
    assert errors == 0, f"{errors} simulated requests failed"
    base = _published("scaler_recovery_s")
    return {
        "metric": f"{name} flash-crowd SLO recovery time "
                  f"(spike {flash.rps_fn(flash.event_at_s):.0f} rps, "
                  f"deviceless sim)",
        "value": round(rec, 1),
        "unit": "s",
        # latency-like metric: smaller is better, vs_baseline inverts
        "vs_baseline": round(base / rec, 3) if base and rec else 1.0,
        "scaler_pod_hours_ratio": ratio,
        "static_peak_replicas": peak_need,
        "scaled_pod_hours": round(dyn.pod_hours, 2),
        "static_pod_hours": round(static.pod_hours, 2),
        "scaled_slo_compliance": round(dyn.slo_compliance(), 4),
        "static_slo_compliance": round(static.slo_compliance(), 4),
        "flips_per_hour": round(crowd.flips_per_hour(), 2),
        "errors": errors,  # MUST be 0: exactly-once terminal contract
    }


def bench_hedge(tiny: bool) -> dict:
    """Request-reliability line: hedged dispatch under the fleet retry
    budget, deviceless and trace-driven (``orchestrate/load_sim.py``).

    One pod of four runs at 20% speed — the classic tail-amplification
    setup: round-robin keeps feeding it, and every request routed there
    waits out its deepening queue. The A/B replays the SAME steady trace
    twice: hedging off (the seed behavior), then hedging on with the
    retry budget funding one tail duplicate per stuck request
    (``retry_pct`` of primary traffic, the cova discipline). The
    promoted value is ``p99_off / p99_on`` — how much tail the hedge
    buys at a bounded (<= 1 + pct) attempt amplification.

    Hard gates, not just numbers: ``errors`` REQUIRED 0 on both runs,
    ``duplicate_executions`` REQUIRED 0 (the loser of every hedge race
    is absorbed by the pod-side idempotency model, never completed
    twice), and every :meth:`SimReport.violations` invariant — including
    the retry-amplification bound — must hold. ``tiny`` shortens the
    trace; the reliability machinery (the REAL ``resilience.hedge``
    classes) is identical.
    """
    from scalable_hw_agnostic_inference_tpu.orchestrate import load_sim

    dur = 600.0 if tiny else 1800.0
    trace = load_sim.SimTrace("slow_pod", dur, lambda t: 4.0, tick_s=15.0)
    kw = dict(static_replicas=4, slow_pods={0: 0.2}, pod_rps=3.0)
    off = load_sim.run_fleet_sim(trace, **kw)
    on = load_sim.run_fleet_sim(trace, hedge=True, retry_pct=0.3, **kw)
    for tag, rep in (("hedge-off", off), ("hedge-on", on)):
        viol = rep.violations()
        assert not viol, f"{tag} invariants violated: {viol}"
    errors = off.errors + on.errors
    assert errors == 0, f"{errors} simulated requests failed"
    dupes = off.double_terminal + on.double_terminal
    assert dupes == 0, f"{dupes} requests executed to completion twice"
    p99_off, p99_on = off.latency_p99(), on.latency_p99()
    assert p99_on > 0, "hedged run completed nothing"
    ratio = round(p99_off / p99_on, 3)
    base = _published("hedge_p99_ratio")
    return {
        "metric": "hedged-dispatch tail rescue (one 5x-slow pod of 4, "
                  "p99 hedge-off/hedge-on, deviceless sim)",
        "value": ratio,
        "unit": "x",
        "vs_baseline": round(ratio / base, 3) if base else 1.0,
        "hedge_p99_ratio": ratio,
        "p99_off_s": round(p99_off, 1),
        "p99_on_s": round(p99_on, 1),
        "hedges_fired": on.hedges,
        "hedges_deduped": on.deduped,
        "attempts": on.attempts,
        "created": on.created,
        "errors": errors,              # MUST be 0
        "duplicate_executions": dupes,  # MUST be 0
    }


def bench_flux(tiny: bool) -> dict:
    """Flux (rectified-flow DiT) txt2img on ONE chip.

    The real flux-schnell is ~12B params — 24 GiB bf16, beyond one v5e chip's
    16 GiB — so this benches a clearly-labeled SCALED geometry (same hidden
    width/heads/patching as flux, depth cut to 6 double + 12 single blocks,
    ~3.8B params) at 256x256, 4 steps, schnell-style (no guidance embedding,
    guidance=0). Self-baselined via BASELINE.json.published like the llama
    benches; the reference's comparable stage is the cova image stage
    (flux-dev 512^2 inf2 TP=8, 5.61 s — ``cova/README.md:98``), recorded in
    BASELINE.md but not directly comparable to a scaled single-chip geometry.
    """
    import dataclasses as _dc

    from scalable_hw_agnostic_inference_tpu.core.aot import (
        host_init,
        to_default_device,
    )
    from scalable_hw_agnostic_inference_tpu.models import flux as flux_mod
    from scalable_hw_agnostic_inference_tpu.models.convert import cast_f32_to_bf16
    from scalable_hw_agnostic_inference_tpu.models.flux_pipeline import FluxPipeline
    from scalable_hw_agnostic_inference_tpu.models.vae import VAEConfig

    if tiny:
        fcfg, vcfg = flux_mod.FluxConfig.tiny(), VAEConfig.tiny()
        size, steps, t5_len = 32, 2, 8
        name = "flux-tiny"
    else:
        fcfg = _dc.replace(flux_mod.FluxConfig.flux_dev(), n_double=6,
                           n_single=12, guidance_embed=False)
        vcfg = VAEConfig(latent_channels=16)
        size, steps, t5_len = 256, 4, 256
        name = "flux-schnell-scaled-4b-geometry"

    model = flux_mod.FluxTransformer(fcfg, dtype=jnp.bfloat16)
    f = 2 ** (len(vcfg.block_out) - 1)
    h = w = size // f
    ids = flux_mod.make_ids(1, t5_len, h, w)  # h,w are LATENT dims
    params = host_init(
        model.init, lambda: jax.random.PRNGKey(0),
        lambda: jnp.zeros((1, (h // 2) * (w // 2), fcfg.in_channels)),
        lambda: jnp.zeros((1, t5_len, fcfg.t5_dim)),
        lambda: jnp.zeros((1, fcfg.clip_dim)),
        lambda: jnp.zeros((1,)),
        lambda: jnp.zeros((1,)),
        lambda: ids,
    )
    params = to_default_device(cast_f32_to_bf16(params))
    from scalable_hw_agnostic_inference_tpu.models.vae import AutoencoderKL

    vae = AutoencoderKL(vcfg)
    vae_params = to_default_device(host_init(
        vae.init, lambda: jax.random.PRNGKey(1),
        lambda: jnp.zeros((1, h, w, vcfg.latent_channels))))

    D_t5, D_clip = fcfg.t5_dim, fcfg.clip_dim

    @jax.jit  # stub conditioning (not benched; cost negligible vs the DiT)
    def t5_encode(tok):
        return jax.nn.one_hot(tok % D_t5, D_t5, dtype=jnp.bfloat16)

    @jax.jit
    def clip_pooled(tok):
        return jax.nn.one_hot(tok[:, 0] % D_clip, D_clip, dtype=jnp.bfloat16)

    pipe = FluxPipeline(fcfg, params, vcfg, vae_params, t5_encode, clip_pooled)
    t5_ids = jnp.zeros((1, t5_len), jnp.int32)
    clip_ids = jnp.zeros((1, 8), jnp.int32)
    rng = jax.random.PRNGKey(0)

    def run(key):
        return pipe.txt2img(t5_ids, clip_ids, rng=key, height=size,
                            width=size, steps=steps, guidance=0.0)

    img = run(rng)  # warm
    runs = 3
    t0 = time.perf_counter()
    for i in range(runs):
        img = run(jax.random.PRNGKey(i))
    dt = (time.perf_counter() - t0) / runs
    assert img.shape[1] == size
    base = _published("flux_scaled_img_s")
    val = round(1.0 / dt, 4)
    return _dollars({
        "metric": f"{name} {size}px {steps}-step txt2img img/s "
                  f"({jax.devices()[0].platform})",
        "value": val,
        "unit": "images/sec",
        "vs_baseline": round(val / base, 3) if base else 1.0,
    })


def bench_t5(tiny: bool) -> dict:
    """T5 embedding throughput on ONE chip (the cova chain's embed stage,
    reference ``t5_model_api.py`` / ``cova/README.md:98``): batched encode +
    mean-pool, sequences/sec. Self-baselined like llama/flux."""
    from scalable_hw_agnostic_inference_tpu.core.aot import (
        host_init,
        to_default_device,
    )
    from scalable_hw_agnostic_inference_tpu.models import t5 as t5_mod
    from scalable_hw_agnostic_inference_tpu.models.convert import cast_f32_to_bf16

    if tiny:
        cfg, batch, seq = t5_mod.T5Config.tiny(), 4, 16
        name = "t5-tiny"
    else:
        cfg, batch, seq = t5_mod.T5Config.t5_v1_1_large(), 32, 128
        name = "t5-v1.1-large-geometry"

    model = t5_mod.T5Encoder(cfg, dtype=jnp.bfloat16)
    params = host_init(
        model.init, lambda: jax.random.PRNGKey(0),
        lambda: jnp.zeros((1, 8), jnp.int32),
        lambda: jnp.ones((1, 8), jnp.int32))
    params = to_default_device(cast_f32_to_bf16(params))

    @jax.jit
    def embed(p, ids, mask):
        return t5_mod.mean_pool(model.apply(p, ids, mask), mask)

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (batch, seq), 3, cfg.vocab_size, jnp.int32)
    mask = jnp.ones((batch, seq), jnp.int32)
    embed(params, ids, mask).block_until_ready()   # warm
    runs = 5
    t0 = time.perf_counter()
    for _ in range(runs):
        out = embed(params, ids, mask)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / runs
    val = round(batch / dt, 2)
    base = _published("t5_embed_seq_s")
    return _dollars({
        "metric": f"{name} embed seq/s (bs={batch}, len={seq}, "
                  f"{jax.devices()[0].platform})",
        "value": val,
        "unit": "sequences/sec",
        "vs_baseline": round(val / base, 3) if base else 1.0,
    })


def bench_mllama(tiny: bool) -> dict:
    """Mllama (Llama-3.2-Vision) CAPTION-path decode on ONE chip: the paged
    engine with gated cross-attention layers attending a full vision-state
    buffer (4 tiles), int8 weights — the cova caption stage's compute
    (reference ``vllm_model_api_m.py`` / ``cova/README.md:98``). 11B text
    geometry born-int8 device-side (models.llama.geometry_params), so it
    fits the chip at every instant; the HBM budget gate validates on boot.
    Self-baselined; end-to-end tok/s for prompt 128 -> 64 new, bs=1.
    """
    import numpy as np

    from scalable_hw_agnostic_inference_tpu.engine import EngineConfig
    from scalable_hw_agnostic_inference_tpu.engine.engine import (
        LLMEngine,
        SamplingParams,
    )
    from scalable_hw_agnostic_inference_tpu.models import llama as llama_mod

    if tiny:
        cfg = llama_mod.LlamaConfig(
            vocab_size=512, dim=64, n_layers=4, n_heads=4, n_kv_heads=2,
            mlp_dim=128, max_seq_len=256, rope_theta=10000.0,
            tie_embeddings=True, cross_attention_layers=(1, 3))
        Lv, prompt_len, new = 34, 16, 8
        ecfg = EngineConfig(max_model_len=64, max_num_seqs=1, block_size=8,
                            context_encoding_buckets=(16,),
                            max_new_tokens=16)
        quant = False
        name = "mllama-tiny"
    else:
        cfg = llama_mod.LlamaConfig.mllama_11b_text()
        Lv = 4 * (1 + (560 // 14) ** 2)        # 4 tiles x (patches+1)
        prompt_len, new = 128, 64
        ecfg = EngineConfig(
            model="meta-llama/Llama-3.2-11B-Vision-Instruct-geometry",
            max_model_len=1024, max_num_seqs=1, block_size=128,
            context_encoding_buckets=(128,), quantization="int8",
            max_new_tokens=128)
        quant = True
        name = "mllama-11b-int8-geometry"

    params = llama_mod.geometry_params(cfg, quant=quant)
    eng = LLMEngine(cfg, params, ecfg, cross_seq_len=Lv)
    states = np.zeros((Lv, cfg.dim), np.float32)
    rng = np.random.default_rng(0)
    prompt = rng.integers(3, cfg.vocab_size, prompt_len).tolist()

    def run(n_new):
        eng.add_request(prompt,
                        SamplingParams(temperature=0.0, max_new_tokens=n_new),
                        cross_states=states, cross_len=Lv)
        fins = []
        while eng.has_work:
            fins += eng.step()
        assert len(fins) == 1 and len(fins[0].token_ids) == n_new
        return fins

    run(2)   # warm: prefill + decode executables + cross projection
    runs = 3
    fins = []
    t0 = time.perf_counter()
    for _ in range(runs):
        fins = run(new)
    dt = (time.perf_counter() - t0) / runs
    val = round(new / dt, 2)
    base = _published("mllama_caption_tok_s")
    out = _dollars({
        "metric": f"{name} caption tok/s (prompt {prompt_len}, Lv={Lv}, "
                  f"bs=1, {jax.devices()[0].platform})",
        "value": val,
        "unit": "tokens/sec",
        "vs_baseline": round(val / base, 3) if base else 1.0,
    })
    out["phases"] = _phases_of(fins)  # last measured request, warm state
    return out


def inner_main() -> None:
    if "--probe" in sys.argv:
        # liveness: a real device round-trip (completion signals can lie
        # over the tunnel — only a host transfer proves execution). A
        # silent JAX CPU fallback must read as DOWN, not alive — a probe
        # that passes on CPU lets the watcher bank cpu-tiny numbers as
        # on-chip measurements (ADVICE r3 medium). Stage markers go to
        # stderr UNBUFFERED so a timed-out probe still tells the parent
        # WHERE the tunnel wedged (r3 postmortems only had "timed out").
        import numpy as np

        def stage(msg):
            print(f"probe-stage: {msg}", file=sys.stderr, flush=True)

        _clear_stale_locks()   # the watcher probes without the parent harness
        stage("backend init (jax.devices)")
        devs = jax.devices()
        stage(f"backend up: {devs[0].platform} x{len(devs)} "
              f"[{getattr(devs[0], 'device_kind', '?')}]")
        if devs[0].platform == "cpu":
            print("probe refused: backend fell back to cpu", file=sys.stderr)
            sys.exit(3)
        stage("compile+enqueue 128x128 bf16 matmul")
        x = jnp.ones((128, 128), jnp.bfloat16)
        y = x @ x
        stage("device->host transfer")
        np.asarray(y)
        stage("round-trip complete")
        print(json.dumps({"metric": "probe", "value": 1.0, "unit": "ok",
                          "vs_baseline": 1.0,
                          "platform": devs[0].platform}))
        return
    tiny = jax.devices()[0].platform == "cpu"
    if not tiny:
        # retries across tunnel failures reuse already-compiled executables
        from scalable_hw_agnostic_inference_tpu.core.aot import (
            enable_persistent_cache_from_env,
        )

        enable_persistent_cache_from_env()
    out = {"llama": bench_llama, "llama_spec": bench_llama_spec,
           "vllm": bench_vllm, "kvtier": bench_kvtier,
           "qos": bench_qos, "disagg": bench_disagg,
           "ragged": bench_ragged, "fused": bench_fused,
           "migrate": bench_migrate, "kvfabric": bench_kvfabric,
           "scaler": bench_scaler, "hedge": bench_hedge,
           "flux": bench_flux, "t5": bench_t5,
           "mllama": bench_mllama, "sd": bench_sd, "sd8": bench_sd8}[
        _which_from_argv(sys.argv)](tiny)
    # structured platform provenance: is_real() keys off this, never off
    # metric-string formatting (ADVICE r3 medium)
    out["platform"] = jax.devices()[0].platform
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# Parent: retry / fallback harness (no accelerator access in this process).
# ---------------------------------------------------------------------------

_STALE_LOCKS = ("/tmp/libtpu_lockfile",)


def _clear_stale_locks() -> None:
    for p in _STALE_LOCKS:
        try:
            os.remove(p)
        except OSError:
            pass


def _run_child(which: str, cpu: bool, timeout: float,
               env: dict | None = None) -> tuple[dict | None, str]:
    """Run one measurement attempt in a child; return (result, error_tail)."""
    args = [sys.executable, os.path.abspath(__file__), "--inner", which]
    for tok in ("llama3b", "int8", "flux", "t5", "mllama", "sd8"):
        if tok in sys.argv and tok not in args:
            args.append(tok)
    if cpu:
        args.append("--cpu")
    try:
        r = subprocess.run(args, capture_output=True, text=True,
                           timeout=timeout,
                           env={**os.environ, **(env or {})})
    except subprocess.TimeoutExpired as te:
        # surface the child's partial stderr: the probe/warm stage markers
        # say exactly WHERE the tunnel wedged (r3's postmortem had only
        # "timed out" to go on)
        tail = _stderr_tail(te.stderr, te.output)
        suffix = f"; last output: {tail}" if tail else ""
        return None, f"attempt timed out after {timeout:.0f}s{suffix}"
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj, ""
    tail = _stderr_tail(r.stderr, r.stdout, lines=4, chars=500)
    return None, tail or f"rc={r.returncode}, no output"


def _stderr_tail(*chunks, lines: int = 3, chars: int = 300) -> str:
    """Last few non-WARNING lines of the first non-empty chunk — ONE
    summarizer for both the timeout and the failed-exit paths."""
    for chunk in chunks:
        if not chunk:
            continue
        if isinstance(chunk, bytes):
            chunk = chunk.decode(errors="replace")
        keep = [ln for ln in chunk.strip().splitlines()
                if "WARNING" not in ln]
        tail = " | ".join(keep[-lines:])[-chars:]
        if tail:
            return tail
    return ""


def _banked_result() -> dict | None:
    """On-chip result banked by the watcher for THIS bench variant, if any."""
    key = _which_from_argv(sys.argv)
    if key == "llama":
        key = "llama3b" if "llama3b" in sys.argv else "llama"
        if "int8" in sys.argv:
            key += "_int8"
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(root, "scripts", "bench_results.json")) as f:
            res = json.load(f).get(key)
        # ONE definition of "real on-device result" (shared with the
        # watcher's done-check and the artifact promoter)
        sys.path.insert(0, os.path.join(root, "scripts"))
        from promote_results import is_real
    except Exception:
        return None
    if is_real(res) and "metric" in res:
        return dict(res)
    return None


def main() -> None:
    which = _which_from_argv(sys.argv)
    unit = UNITS_BY_BENCH.get(which, "images/sec")
    force_cpu = "--cpu" in sys.argv

    last_err = ""
    attempts = 1 if force_cpu else 3
    for i in range(attempts):
        _clear_stale_locks()
        if not force_cpu:
            # cheap liveness gate: a WEDGED tunnel hangs in backend init
            # without erroring — probing first (3 min cap) keeps a dead
            # backend from burning the full measurement timeout per attempt
            probe, perr = _run_child("--probe", cpu=False, timeout=180)
            if probe is None:
                last_err = f"device probe failed: {perr}"
                if i + 1 < attempts:
                    time.sleep(20 * (i + 1))
                continue
        # last-attempt escalation for sd: the fused-pipeline mega-compile is
        # the known tunnel-wedger; stepwise mode compiles only the (already
        # cache-banked) single-step + decode executables
        env = ({"SHAI_SD_STEPWISE": "1"}
               if which == "sd" and not force_cpu and i == attempts - 1
               else None)
        out, last_err = _run_child(which, force_cpu, timeout=2400, env=env)
        if out is not None:
            # a measurement child whose backend silently fell back to CPU is
            # a FAILED attempt, not a result: banking it would block the
            # real on-chip number for the rest of the round (the probe
            # passing does not guarantee the next child's init succeeds)
            if not force_cpu and out.get("platform") == "cpu":
                last_err = "measurement child fell back to cpu platform"
                if i + 1 < attempts:
                    time.sleep(20 * (i + 1))
                continue
            print(json.dumps(out))
            return
        if i + 1 < attempts:
            time.sleep(20 * (i + 1))

    # TPU never came up now — but the watcher (scripts/bench_watch.sh) may
    # have measured this bench on the chip earlier in the round, whenever
    # the tunnel was briefly alive. A banked on-chip number from the same
    # code is a far better record than a cpu-tiny fallback; emit it clearly
    # labeled.
    if not force_cpu:
        banked = _banked_result()
        if banked is not None:
            # honest provenance: exactly when and at which commit the
            # watcher measured this, never "same code" — commits may have
            # landed since
            banked["note"] = (
                f"banked on-chip measurement from scripts/bench_watch.sh "
                f"(commit {banked.pop('commit', 'unknown')}, "
                f"measured_at {banked.pop('measured_at', 'unknown')}); "
                f"live tunnel down at bench time: {last_err[-200:]}")
            print(json.dumps(banked))
            return

    # still emit a valid line from a CPU-tiny run so the driver records a
    # measurement (clearly marked) instead of a crash dump.
    if not force_cpu:
        out, cpu_err = _run_child(which, cpu=True, timeout=900)
        if out is not None:
            out["error"] = f"tpu backend unavailable, cpu-tiny fallback: {last_err}"
            out["vs_baseline"] = 0.0
            print(json.dumps(out))
            return
        last_err = f"{last_err}; cpu fallback also failed: {cpu_err}"

    print(json.dumps({
        "metric": f"{which} bench failed (backend unavailable)",
        "value": 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "error": last_err[-700:],
    }))


if __name__ == "__main__":
    if INNER:
        inner_main()
    else:
        try:
            main()
        except BaseException as e:  # the driver must ALWAYS get one JSON line
            print(json.dumps({
                "metric": "bench harness crashed",
                "value": 0.0,
                "unit": UNITS_BY_BENCH.get(_which_from_argv(sys.argv),
                                            "images/sec"),
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}"[:700],
            }))
        sys.exit(0)
