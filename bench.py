"""Round-end benchmark: prints ONE JSON line for the driver.

Headline metric (BASELINE.json north star): causal-LM decode throughput on a
single chip — Llama-3.2-1B geometry with random bf16 weights, bucketed
prefill + ``lax.scan`` decode (the same jit-once generate path serving uses).
``vs_baseline`` is the ratio to BASELINE.json's published figure when one
exists; 1.0 marks "no prior round published" (round 1 sets the bar).

Usage: ``python bench.py`` (runs on whatever platform JAX sees; the driver
gives it the one real TPU chip).
"""

from __future__ import annotations

import json
import sys
import time

import jax

if "--cpu" in sys.argv:  # local smoke; env-var JAX_PLATFORMS is captured too early
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from scalable_hw_agnostic_inference_tpu.models.generate import make_generate
from scalable_hw_agnostic_inference_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)

# Llama-3.2-1B geometry (HF config.json: hidden 2048, 16 layers, 32 heads,
# 8 kv heads, mlp 8192, vocab 128256) — the model the reference serves via
# vllm_model_api.py on neuron.
CFG_1B = LlamaConfig(
    vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
    mlp_dim=8192, max_seq_len=4096, rope_theta=500000.0, tie_embeddings=True,
)

BATCH = 8
PROMPT_BUCKET = 128
MAX_NEW = 128


def main() -> None:
    platform = jax.devices()[0].platform
    if platform == "cpu":  # keep a CPU smoke run fast
        cfg, batch, prompt, new = LlamaConfig.tiny(), 2, 32, 16
    else:
        cfg, batch, prompt, new = CFG_1B, BATCH, PROMPT_BUCKET, MAX_NEW

    model = LlamaForCausalLM(cfg, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    params = jax.jit(model.init)(rng, jnp.zeros((1, 8), jnp.int32))
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16)
                          if a.dtype == jnp.float32 else a, params)

    gen = make_generate(model, cfg, prompt_bucket=prompt, max_new_tokens=new,
                        eos_id=-1)  # never hit EOS: measure full decode
    ids = jax.random.randint(rng, (batch, prompt), 3, cfg.vocab_size, jnp.int32)
    plen = jnp.full((batch,), prompt, jnp.int32)

    # compile + warmup
    out = gen(params, ids, plen, rng, 1.0, 0, 1.0)
    out.tokens.block_until_ready()

    runs = 3
    t0 = time.perf_counter()
    for i in range(runs):
        out = gen(params, ids, plen, jax.random.fold_in(rng, i), 1.0, 0, 1.0)
    out.tokens.block_until_ready()
    dt = (time.perf_counter() - t0) / runs
    toks_per_s = batch * new / dt

    try:
        published = json.load(open("BASELINE.json"))["published"]
        base = published.get("llama1b_decode_tok_s")
    except Exception:
        base = None
    print(json.dumps({
        "metric": f"llama3.2-1b-geometry decode tok/s (bs={batch}, {platform})",
        "value": round(toks_per_s, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(toks_per_s / base, 3) if base else 1.0,
    }))


if __name__ == "__main__":
    main()
