// loadgen — native closed-loop HTTP load generator (L5 instrumentation).
//
// Parity target: the reference's synthetic client loop (`app/call-model.sh:6-10`,
// one curl per replica) and the breaking-point finder's demand source
// (`find-compute-breaking-point.yaml:20-59`). A shell curl loop cannot hold
// precise concurrency or measure tail latency; this native client drives N
// concurrent closed-loop connections and emits the same percentile report
// shape as serve/latency.py, as one JSON line.
//
// Build: make -C native     Usage:
//   loadgen --url http://host:port/path [--method POST --body '{"x":1}']
//           [--concurrency 8] [--duration 30] [--warmup 2]
//
// Single file, C++17, POSIX sockets only (no third-party deps).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

using Clock = std::chrono::steady_clock;

struct Url {
    std::string host, port, path;
};

static bool parse_url(const std::string &u, Url &out) {
    const std::string pre = "http://";
    if (u.rfind(pre, 0) != 0) return false;
    auto rest = u.substr(pre.size());
    auto slash = rest.find('/');
    out.path = slash == std::string::npos ? "/" : rest.substr(slash);
    auto hostport = rest.substr(0, slash);
    auto colon = hostport.find(':');
    out.host = hostport.substr(0, colon);
    out.port = colon == std::string::npos ? "80" : hostport.substr(colon + 1);
    return !out.host.empty();
}

static int dial(const Url &u) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(u.host.c_str(), u.port.c_str(), &hints, &res) != 0)
        return -1;
    int fd = -1;
    for (auto *p = res; p; p = p->ai_next) {
        fd = socket(p->ai_family, p->ai_socktype, p->ai_protocol);
        if (fd < 0) continue;
        timeval tv{300, 0};  // generous: covers cold-compile responses
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
        if (connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
        close(fd);
        fd = -1;
    }
    freeaddrinfo(res);
    return fd;
}

// one full request/response on a fresh connection; returns HTTP status or
// -1. `ttfb` (seconds from request start) is set when the first BODY byte
// arrives — for SSE responses that is the first token event, so under
// --body '{"stream": true}' payloads the ttfb percentiles are the unit's
// TTFT (the LLM serving SLO; breaking_point.py --slo ttfb gates on it).
static int once(const Url &u, const std::string &req, double &ttfb) {
    auto t0 = Clock::now();
    ttfb = -1.0;
    int fd = dial(u);
    if (fd < 0) return -1;
    size_t off = 0;
    while (off < req.size()) {
        ssize_t n = send(fd, req.data() + off, req.size() - off, 0);
        if (n <= 0) { close(fd); return -1; }
        off += size_t(n);
    }
    // read status line + drain until close (we send Connection: close)
    char buf[8192];
    std::string head;
    int status = -1;
    bool in_body = false;
    while (true) {
        ssize_t n = recv(fd, buf, sizeof buf, 0);
        if (n <= 0) break;
        if (!in_body) {
            head.append(buf, size_t(n));
            if (status < 0) {
                auto sp = head.find(' ');
                if (sp != std::string::npos && head.size() >= sp + 4)
                    status = std::atoi(head.c_str() + sp + 1);
            }
            auto he = head.find("\r\n\r\n");
            if (he != std::string::npos && head.size() > he + 4) {
                in_body = true;   // this recv carried the first body bytes
                ttfb = std::chrono::duration<double>(Clock::now() - t0).count();
            }
        }
    }
    close(fd);
    return status;
}

int main(int argc, char **argv) {
    std::string url, method = "GET", body;
    int concurrency = 8, duration = 30, warmup = 2;
    for (int i = 1; i < argc - 1; i++) {
        std::string a = argv[i];
        if (a == "--url") url = argv[++i];
        else if (a == "--method") method = argv[++i];
        else if (a == "--body") body = argv[++i];
        else if (a == "--concurrency") concurrency = std::atoi(argv[++i]);
        else if (a == "--duration") duration = std::atoi(argv[++i]);
        else if (a == "--warmup") warmup = std::atoi(argv[++i]);
    }
    Url u;
    if (url.empty() || !parse_url(url, u)) {
        std::fprintf(stderr,
                     "usage: loadgen --url http://h:p/path [--method M] "
                     "[--body B] [--concurrency N] [--duration S] [--warmup S]\n");
        return 2;
    }
    std::string req = method + " " + u.path + " HTTP/1.1\r\n" +
                      "Host: " + u.host + "\r\n" +
                      "Connection: close\r\n";
    if (!body.empty())
        req += "Content-Type: application/json\r\nContent-Length: " +
               std::to_string(body.size()) + "\r\n";
    req += "\r\n" + body;

    std::mutex mu;
    std::vector<double> lat, lat_ttfb;
    std::atomic<long> ok{0}, errs{0}, non200{0};
    auto t_end = Clock::now() + std::chrono::seconds(duration + warmup);
    auto t_measure = Clock::now() + std::chrono::seconds(warmup);

    std::vector<std::thread> ts;
    for (int i = 0; i < concurrency; i++)
        ts.emplace_back([&] {
            while (Clock::now() < t_end) {
                auto t0 = Clock::now();
                double ttfb = -1.0;
                int status = once(u, req, ttfb);
                double dt = std::chrono::duration<double>(Clock::now() - t0).count();
                if (Clock::now() < t_measure) continue;  // warmup discard
                if (status < 0) { errs++; continue; }
                if (status != 200) { non200++; continue; }
                ok++;
                std::lock_guard<std::mutex> g(mu);
                lat.push_back(dt);
                if (ttfb >= 0.0) lat_ttfb.push_back(ttfb);
            }
        });
    for (auto &t : ts) t.join();

    std::sort(lat.begin(), lat.end());
    std::sort(lat_ttfb.begin(), lat_ttfb.end());
    auto pct_of = [](const std::vector<double> &v, double p) -> double {
        if (v.empty()) return 0.0;
        size_t i = size_t(p * double(v.size() - 1) + 0.5);
        return v[std::min(i, v.size() - 1)];
    };
    auto pct = [&](double p) { return pct_of(lat, p); };
    double rps = double(ok.load()) / double(duration);
    // same report shape as serve/latency.py's percentile report, plus the
    // first-body-byte percentiles (TTFT under SSE streaming bodies)
    std::printf(
        "{\"n_runs\": %ld, \"throughput_rps\": %.3f, \"errors\": %ld, "
        "\"non_200\": %ld, \"p0\": %.4f, \"p50\": %.4f, \"p90\": %.4f, "
        "\"p95\": %.4f, \"p99\": %.4f, \"p100\": %.4f, "
        "\"ttfb_p50\": %.4f, \"ttfb_p90\": %.4f, \"ttfb_p99\": %.4f}\n",
        ok.load(), rps, errs.load(), non200.load(), pct(0.0), pct(0.5),
        pct(0.9), pct(0.95), pct(0.99), pct(1.0), pct_of(lat_ttfb, 0.5),
        pct_of(lat_ttfb, 0.9), pct_of(lat_ttfb, 0.99));
    return 0;
}
