#!/usr/bin/env bash
# Per-pod NodePort debug exposer — parity with the reference operator tool
# (app/create_node_port_svc.sh + node_port_svc_template.yaml): label ONE
# serving pod and surface it on its node's IP, bypassing the gateway, so an
# operator can curl a specific replica (per-pod /stats, /profile, latency).
#
# Usage: POD_NAME=sd21-tpu-abc123 bash deploy/debug/create_node_port_svc.sh
# Cleanup: kubectl delete svc "$POD_NAME-debug"; kubectl label pod \
#          "$POD_NAME" inferencepod-
set -euo pipefail

: "${POD_NAME:?set POD_NAME to the pod to expose}"

kubectl label pod "$POD_NAME" "inferencepod=$POD_NAME" --overwrite

# node this pod landed on + that node's reachable IP (external if the pool
# has one, internal otherwise — GKE TPU pools are usually internal-only)
NODE=$(kubectl get pod "$POD_NAME" -o jsonpath='{.spec.nodeName}')
NODE_IP=$(kubectl get node "$NODE" \
  -o jsonpath='{.status.addresses[?(@.type=="ExternalIP")].address}')
[ -n "$NODE_IP" ] || NODE_IP=$(kubectl get node "$NODE" \
  -o jsonpath='{.status.addresses[?(@.type=="InternalIP")].address}')

export POD_NAME SVC_NAME="$POD_NAME-debug"
envsubst < "$(dirname "$0")/node-port-svc-template.yaml" | kubectl apply -f -

PORT=$(kubectl get svc "$SVC_NAME" -o jsonpath='{.spec.ports[0].nodePort}')
echo "pod $POD_NAME exposed at http://$NODE_IP:$PORT (node $NODE)"
