#!/usr/bin/env python3
"""Render the (model, hardware) deployment-unit matrix into deploy/units/.

The reference hand-maintains one Deployment+Service YAML per
(model, instance-family, framework) triple (``sd21-inf2-deploy.yaml`` etc.,
SURVEY.md §2.4 L4). Here the matrix is a table and the YAML is generated —
`python deploy/gen_units.py` rewrites deploy/units/ deterministically; the
generated files are committed so the tree is kubectl-appliable as-is.
"""

from __future__ import annotations

import os
import textwrap

IMAGE = "ghcr.io/example/shai-tpu:latest"        # templated by build/build.sh
# v5e podslice topology per requested chip count (GKE nodepool contract)
TPU_TOPOLOGY = {1: "1x1", 4: "2x2", 8: "2x4"}
CPU_SELECTOR = {"nodepool": "cpu-compute"}

# (app, model-name-in-registry, tier, env-overrides, tpu-chips)
#
# Tier naming: any tier starting with "tpu" runs DEVICE=tpu on v5e (the
# suffix distinguishes config flavors of the same silicon, the way the
# reference's g5-cuda vs g5-triton are the same GPU under two frameworks —
# sd21-weighted-routing-ing.yaml routes across BOTH). Each tier gets its own
# nodepool label, so per-tier counters/KEDA stay separable.
UNITS = [
    # SD_BATCH_MAX: concurrent requests coalesce into one batched denoise
    # (pow2 buckets, per-request seeds preserved) — the throughput/$ lever
    # the breaking-point ramp measures; batch-4 activations fit the chip
    # with the bf16 UNet (core.budget accounting)
    # latency tier keeps the MEASURED on-chip dispatch policy (r3
    # perf_attn: XLA attention won at batch 1-2, which is what this tier
    # serves at low occupancy). The perf model says flash wins at batch 4
    # (PERF_MODEL.md) — the watcher's measured ramp decides before flash
    # becomes this tier's default; the batch-8 tier below already runs it.
    ("sd21", "sd", "tpu", {"MODEL_ID": "stabilityai/stable-diffusion-2-1-base",
                           "HEIGHT": "512", "WIDTH": "512",
                           "NUM_INFERENCE_STEPS": "25",
                           "SD_BATCH_MAX": "4"}, 1),
    # throughput flavor of the same chip: deeper coalescing (batch 8) —
    # higher img/s/$ at higher tail latency. Two sd21 TPU tiers with
    # DIFFERENT measured breakpoints is what makes the weighted route a real
    # cost decision (reference sd21-weighted-routing-ing.yaml:19-20 routes
    # five tiers at 15/15/10/40/20; VERDICT r4 missing #2)
    ("sd21", "sd", "tpub8", {"MODEL_ID":
                             "stabilityai/stable-diffusion-2-1-base",
                             "HEIGHT": "512", "WIDTH": "512",
                             "NUM_INFERENCE_STEPS": "25",
                             "SD_BATCH_MAX": "8",
                             # throughput tier runs flash attention on every
                             # UNet level: the offline perf model
                             # (PERF_MODEL.md) shows XLA-attention batched
                             # steps are HBM-bound on score traffic while
                             # flash flips them MXU-bound (b4: 48.7 -> 21.7
                             # GB/step); watcher re-validates on-chip
                             "SHAI_ATTN_IMPL": "pallas"}, 1),
    ("bert", "bert", "tpu", {"MODEL_ID":
                             "distilbert-base-uncased-finetuned-sst-2-english"}, 1),
    ("bert", "bert", "cpu", {"MODEL_ID":
                             "distilbert-base-uncased-finetuned-sst-2-english"}, 0),
    # capacity-failover backstop (the scaledobjects' cpu tier): REAL weights
    # — slow on CPU but correct. Steady-state weighted routing sends it no
    # traffic (deploy/ingress/sd21-weighted-routing-ing.yaml is tpu-only);
    # it only serves when the capacity-checker swaps to equal routing and
    # the TPU tier has lost capacity (SURVEY.md §3.5 failover semantics).
    ("sd21", "sd", "cpu", {"MODEL_ID": "stabilityai/stable-diffusion-2-1-base",
                           "HEIGHT": "512", "WIDTH": "512",
                           "NUM_INFERENCE_STEPS": "25"}, 0),
    ("vit", "vit", "tpu", {"MODEL_ID": "google/vit-base-patch16-224"}, 1),
    ("llama", "llama", "tpu", {"MODEL_ID": "meta-llama/Meta-Llama-3-8B",
                               "MESH_SPEC": "tp=4", "MAX_NEW_TOKENS": "128"}, 4),
    # the reference's mistral/ manifest family (mistral-trn-deploy.yaml):
    # same causal-LM service, Mistral checkpoint, tp=4 like the llama unit
    ("mistral", "mistral", "tpu",
     {"MODEL_ID": "mistralai/Mistral-7B-Instruct-v0.3",
      "MESH_SPEC": "tp=4", "MAX_NEW_TOKENS": "128"}, 4),
    # single-chip DeepSeek distill (reference app/deepseek_model_api.py):
    # int8 weight-only puts the 8B at ~8.3 GiB params — fits one 16 GiB v5e
    # chip with KV + activations (core.budget; tests/test_budget.py pins it)
    ("deepseek", "deepseek", "tpu",
     {"MODEL_ID": "deepseek-ai/DeepSeek-R1-Distill-Llama-8B",
      "QUANTIZATION": "int8", "MAX_NEW_TOKENS": "128"}, 1),
    ("vllm", "vllm", "tpu", {"MODEL_ID": "meta-llama/Llama-3.2-1B"}, 1),
    ("t5", "t5", "tpu", {"MODEL_ID": "google/t5-v1_1-large",
                         "MESH_SPEC": "tp=4"}, 4),
    ("yolo", "yolo", "tpu", {"MODEL_ID": "hustvl/yolos-tiny"}, 1),
    # the reference's flagship multi-chip demo unit (flux_model_api on
    # inf2-TP8, SURVEY.md §2.2): one v5e-8 host, submesh packing — CLIP+VAE
    # on chips 0-1, T5 + transformer TP over the rest (serve/services.py
    # FluxService SUBMESH contract)
    ("flux", "flux", "tpu", {"MODEL_ID": "black-forest-labs/FLUX.1-schnell",
                             "HEIGHT": "512", "WIDTH": "512",
                             "SUBMESH": "2:8",
                             "NUM_INFERENCE_STEPS": "4"}, 8),
]



def _is_tpu(tier: str) -> bool:
    """tpu / tpub8 / ... — config flavors of the v5e tier (see UNITS note)."""
    return tier.startswith("tpu")


def _selector_yaml(tier: str, chips: int) -> str:
    if _is_tpu(tier):
        n = max(chips, 1)
        if n not in TPU_TOPOLOGY:
            raise ValueError(
                f"no v5e topology mapped for {n} chips — add it to "
                f"TPU_TOPOLOGY (have {sorted(TPU_TOPOLOGY)})")
        selector = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": TPU_TOPOLOGY[n],
        }
    else:
        selector = CPU_SELECTOR
    return "".join(f"        {k}: {v}\n" for k, v in selector.items())


def _env_yaml(env_all: dict) -> str:
    return "".join(
        f"""        - name: {k}
          value: "{v}"
"""
        for k, v in env_all.items())


def _resources_yaml(chips: int) -> str:
    if not chips:
        return ""
    return f"""        resources:
          requests:
            google.com/tpu: "{chips}"
          limits:
            google.com/tpu: "{chips}"
"""


def render_unit(app: str, model: str, tier: str, env: dict, chips: int) -> str:
    name = f"{app}-{tier}"
    env_all = {
        "APP": app, "MODEL": model, "DEVICE": "tpu" if _is_tpu(tier) else "cpu",
        "NODEPOOL": f"{tier}-pool", "PORT": "8000",
        "ARTIFACT_ROOT": "/artifacts", **env,
    }
    env_yaml = _env_yaml(env_all)
    sel_yaml = _selector_yaml(tier, chips)
    resources = _resources_yaml(chips)
    return f"""# GENERATED by deploy/gen_units.py — edit the matrix there.
# Deployment unit ({app}, {tier}, shai-tpu) — reference L4 pattern
# (sd21-inf2-deploy.yaml / *-svc.yaml, SURVEY.md 2.4).
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}
  labels:
    app: {name}
    albapp: {app}          # shared label: equal-routing service selector
spec:
  replicas: 1
  selector:
    matchLabels:
      app: {name}
  template:
    metadata:
      labels:
        app: {name}
        albapp: {app}
      annotations:
        prometheus.io/scrape: "true"
        prometheus.io/port: "8000"
        prometheus.io/path: "/metrics"
    spec:
      nodeSelector:
{sel_yaml}      containers:
      - name: model
        image: {IMAGE}
        command: ["python", "-m", "scalable_hw_agnostic_inference_tpu.serve", "{model}"]
        ports:
        - containerPort: 8000
        env:
        - name: POD_NAME
          valueFrom:
            fieldRef:
              fieldPath: metadata.name
{env_yaml}{resources}        readinessProbe:
          httpGet: {{path: /readiness, port: 8000}}
          initialDelaySeconds: 30
          periodSeconds: 10
          failureThreshold: 120    # cold compile can take minutes
        livenessProbe:
          httpGet: {{path: /health, port: 8000}}
          initialDelaySeconds: 30
          periodSeconds: 30
        volumeMounts:
        - name: artifacts
          mountPath: /artifacts
      volumes:
      - name: artifacts
        persistentVolumeClaim:
          claimName: shai-artifacts
---
apiVersion: v1
kind: Service
metadata:
  name: {name}
  labels:
    app: {name}
spec:
  selector:
    app: {name}
  ports:
  - port: 80
    targetPort: 8000
"""


def render_job(app: str, model: str, tier: str, env: dict, chips: int) -> str:
    """The artifact-producing compile Job — reference
    ``compile-vllm-job.yaml:22-62`` (VERDICT r1 #8 / r2 missing #2): mounts
    the shared artifacts PVC and runs ``compilectl`` so serving pods boot
    from a warm XLA cache + exported StableHLO instead of cold-compiling
    behind the LB."""
    name = f"compile-{app}-{tier}"
    env_all = {
        "APP": app, "MODEL": model, "DEVICE": "tpu" if _is_tpu(tier) else "cpu",
        "NODEPOOL": f"{tier}-pool", "ARTIFACT_ROOT": "/artifacts", **env,
    }
    env_yaml = _env_yaml(env_all)
    sel_yaml = _selector_yaml(tier, chips)
    resources = _resources_yaml(chips)
    return f"""# GENERATED by deploy/gen_units.py — edit the matrix there.
# Compile Job ({app}, {tier}) — reference compile-vllm-job.yaml:22-62.
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  backoffLimit: 2
  template:
    metadata:
      labels:
        job: {name}
    spec:
      restartPolicy: Never
      nodeSelector:
{sel_yaml}      containers:
      - name: compile
        image: {IMAGE}
        command: ["python", "-m",
                  "scalable_hw_agnostic_inference_tpu.compilectl", "{model}"]
        env:
        - name: POD_NAME
          valueFrom:
            fieldRef:
              fieldPath: metadata.name
{env_yaml}{resources}        volumeMounts:
        - name: artifacts
          mountPath: /artifacts
      volumes:
      - name: artifacts
        persistentVolumeClaim:
          claimName: shai-artifacts
"""


# (name, model-registry-name, MODEL_ID, hosts, chips/host, topology,
#  MESH_SPEC, extra env) — multi-host slice units: ONE JAX cluster per
# StatefulSet, leader serves, followers mirror (serve/multihost.py).
# Geometries are HBM-budget-validated (core.budget, tests/test_budget.py).
MH_UNITS = [
    ("llama-mh", "llama", "meta-llama/Meta-Llama-3-70B", 4, 4, "4x4",
     "tp=16", {"MAX_NEW_TOKENS": "128"}),
    # the reference's biggest deployment: DeepSeek-R1-Distill-Llama-70B at
    # TP=32 (compile-vllm-job.yaml:49-55 — compiled there at len=128/bs=1;
    # here the full 8192 window fits the budget at bf16: params 4.4 +
    # replicated-GQA KV 2.7 GiB/chip, see tests/test_budget.py)
    ("dsr70b-mh", "deepseek", "deepseek-ai/DeepSeek-R1-Distill-Llama-70B",
     8, 4, "4x8", "tp=32", {"MAX_NEW_TOKENS": "128"}),
]


def render_mh_unit(name: str, model: str, model_id: str, hosts: int,
                   chips_per_host: int, topology: str, mesh_spec: str,
                   extra_env: dict) -> str:
    env_all = {
        "APP": model, "DEVICE": "tpu", "NODEPOOL": "tpu-pool",
        "PORT": "8000", "ARTIFACT_ROOT": "/artifacts", "MODEL_ID": model_id,
        "MESH_SPEC": mesh_spec, **extra_env,
        "SHAI_COORDINATOR": f"{name}-0.{name}:8476",
        "SHAI_NUM_PROCESSES": str(hosts),
    }
    env_yaml = _env_yaml(env_all)
    return f"""# GENERATED by deploy/gen_units.py — edit MH_UNITS there.
# Multi-host slice unit: ONE model spanning the {hosts} hosts of a
# v5e-{hosts * chips_per_host} slice ({topology} topology, {mesh_spec}).
# The reference's biggest unit is TP=32 over 8 Neuron devices of one trn1
# host via the vLLM/NxD fork (compile-vllm-job.yaml:38-55); past one host it
# would need NxD's EFA collectives. TPU-natively a multi-host slice is ONE
# JAX cluster: each pod of this StatefulSet is one host process, pod ordinal
# 0 is the coordinator (core.device.maybe_distributed_init), and the mesh
# spans all hosts — XLA routes collectives over ICI, no NCCL/MPI tier.
#
# Failure semantics are fail-together: jax.distributed's heartbeat kills
# every process when a peer dies (there is no single-pod rejoin), the
# StatefulSet restarts the pods in parallel, and the cluster re-forms —
# the same whole-unit restart the reference pays when a vLLM rank dies.
apiVersion: v1
kind: Service
metadata:
  name: {name}
  labels:
    app: {name}
spec:
  clusterIP: None          # headless: stable per-pod DNS for the coordinator
  # cluster formation happens BEFORE readiness (pods become Ready only after
  # distributed init + load + warm) — the coordinator's DNS record must
  # exist for not-ready pods or formation deadlocks
  publishNotReadyAddresses: true
  selector:
    app: {name}
  ports:
  - name: http
    port: 8000
  - name: coord
    port: 8476
---
# HTTP entrypoint: ONLY the leader (pod ordinal 0) serves task routes —
# followers mirror its work over the broadcast channel (serve/multihost.py)
# and expose just the probes. Routing a /generate to a follower would 404,
# so this Service pins to the leader pod; the unit joins weighted/equal
# routing through it, not through the per-pod albapp label.
apiVersion: v1
kind: Service
metadata:
  name: {name}-http
  labels:
    app: {name}
spec:
  selector:
    statefulset.kubernetes.io/pod-name: {name}-0
  ports:
  - name: http
    port: 80
    targetPort: 8000
---
apiVersion: apps/v1
kind: StatefulSet
metadata:
  name: {name}
  labels:
    app: {name}
spec:
  serviceName: {name}
  replicas: {hosts}              # hosts of the slice ({chips_per_host} chips each)
  podManagementPolicy: Parallel   # all hosts must start to form the cluster
  selector:
    matchLabels:
      app: {name}
  template:
    metadata:
      labels:
        app: {name}
      annotations:
        prometheus.io/scrape: "true"
        prometheus.io/port: "8000"
        prometheus.io/path: "/metrics"
    spec:
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: tpu-v5-lite-podslice
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
      - name: model
        image: {IMAGE}
        command: ["/bin/sh", "-c"]
        # the pod ordinal (StatefulSet suffix) is the JAX process id
        args:
        - |
          export SHAI_PROCESS_ID="${{POD_NAME##*-}}"
          exec python -m scalable_hw_agnostic_inference_tpu.serve {model}
        ports:
        - containerPort: 8000
        env:
        - name: POD_NAME
          valueFrom:
            fieldRef:
              fieldPath: metadata.name
{env_yaml}        resources:
          requests:
            google.com/tpu: "{chips_per_host}"
          limits:
            google.com/tpu: "{chips_per_host}"
        volumeMounts:
        - name: artifacts
          mountPath: /artifacts
        # cluster formation blocks in jax.distributed.initialize BEFORE any
        # socket binds — a pod waiting for delayed peers (node provisioning,
        # image pull) serves nothing. The startupProbe owns that window
        # (~20 min) so liveness can't kill pods mid-formation and crash-loop
        # the whole unit on a cold deploy.
        startupProbe:
          httpGet:
            path: /health
            port: 8000
          periodSeconds: 10
          failureThreshold: 120
        readinessProbe:
          httpGet:
            path: /readiness
            port: 8000
          periodSeconds: 10
          failureThreshold: 60
        livenessProbe:
          httpGet:
            path: /health
            port: 8000
          periodSeconds: 30
      volumes:
      - name: artifacts
        persistentVolumeClaim:
          claimName: shai-artifacts
"""


# ---------------------------------------------------------------------------
# control-plane numbers: DERIVED from measurements, never invented.
# deploy/derived_weights.json is produced by scripts/derive_weights.py from
# banked breaking-point measurements (deploy/breakpoints.json) + the $/hr
# basis in BASELINE.json — the reference's measured-breakpoint -> ALB-weight
# -> KEDA-target math (README.md:183-233), reproduced as a committed,
# regenerable derivation (VERDICT r3 missing #1 / weak #3).
# ---------------------------------------------------------------------------

_MAX_REPLICAS = {"tpu": 20, "cpu": 10}


def _load_derived():
    path = os.path.join(os.path.dirname(__file__), "derived_weights.json")
    if not os.path.exists(path):
        return None
    import json

    with open(path) as f:
        return json.load(f)


def _provenance(row: dict) -> str:
    tag = "PROJECTED" if row.get("projected") else "measured"
    return (f"{tag} breakpoint {row['breakpoint_rps']} RPS "
            f"({row['platform']} @{row['commit']})")


def render_scaledobjects(app: str, units: dict, mode: str) -> str:
    """One ScaledObject per unit; targets derived per mode (see header)."""
    target_key = f"keda_{mode}_target"
    what = ("cost-optimized (weighted)" if mode == "weighted"
            else "capacity-optimized (equal)")
    formula = ("breakpoint RPS (per-replica capacity at the 900 ms p50 SLO)"
               if mode == "weighted"
               else "0.70 x breakpoint RPS (reference README.md:235)")
    docs = []
    for key in sorted(units):
        row = units[key]
        tier = key.rsplit("-", 1)[1]
        # scale-out signal: this unit's own traffic share (nodepool label is
        # stamped by serve/metrics.py from the NODEPOOL env)
        query = (f'sum(rate(shai_requests_total{{app="{app}", '
                 f'nodepool="{tier}-pool"}}[1m]))')
        docs.append(f"""apiVersion: keda.sh/v1alpha1
kind: ScaledObject
metadata:
  name: {key}-{mode}
spec:
  scaleTargetRef:
    name: {key}
  minReplicaCount: 1        # keep every tier warm (reference :12)
  maxReplicaCount: {_MAX_REPLICAS["tpu" if _is_tpu(tier) else "cpu"]}
  cooldownPeriod: 300
  triggers:
  - type: prometheus
    metadata:
      serverAddress: http://prometheus.monitoring:9090
      query: {query}
      # {formula}
      # = {_provenance(row)}
      threshold: "{row[target_key]}"
""")
    header = f"""# GENERATED by deploy/gen_units.py — numbers DERIVED, not edited here.
# KEDA autoscaling, {what} mode — reference
# sd21-scaledobject-{mode}-routing.yaml. Reference triggers on a CloudWatch
# metric-math SUM of the per-app counter; the TPU-native signal is the same
# counter exported as Prometheus `shai_requests_total` (serve/metrics.py).
# Every threshold below is derived from a banked breaking-point measurement
# (deploy/breakpoints.json -> scripts/derive_weights.py): {formula}.
"""
    return header + "---\n".join(docs)


def render_weighted_route(app: str, units: dict) -> str:
    """Cost-optimized HTTPRoute: weights = normalized throughput/$ shares."""
    in_route = {k: r for k, r in sorted(units.items()) if "weight_pct" in r}
    backends = "".join(f"""    - name: {key}
      port: 80
      # weight = rps_per_dollar_hr share: {row['rps_per_dollar_hr']} RPS/$hr
      # from {_provenance(row)}
      weight: {row['weight_pct']}
""" for key, row in in_route.items())
    return f"""# GENERATED by deploy/gen_units.py — numbers DERIVED, not edited here.
# Cost-optimized (weighted) routing — reference sd21-weighted-routing-ing.yaml:19-20.
# The reference encodes weights in an ALB annotation; the portable TPU-native
# form is Gateway API weighted backendRefs. Weights are the normalized
# throughput-per-dollar shares from measured breaking points (the reference's
# cost-per-inference ranking, README.md:183-233, inverted to throughput/$) —
# see deploy/derived_weights.json. The cpu failover backstop is deliberately
# absent: cost mode sends it nothing; it serves only under equal/failover
# routing (shared albapp label) when the primary tiers lose capacity.
apiVersion: gateway.networking.k8s.io/v1
kind: HTTPRoute
metadata:
  name: {app}-weighted
spec:
  parentRefs:
  - name: shai-gateway
  rules:
  - backendRefs:
{backends}    timeouts:
      request: 300s   # covers the longest denoise at the breaking point
"""


def main() -> None:
    out_dir = os.path.join(os.path.dirname(__file__), "units")
    jobs_dir = os.path.join(os.path.dirname(__file__), "jobs")
    os.makedirs(out_dir, exist_ok=True)
    os.makedirs(jobs_dir, exist_ok=True)
    for app, model, tier, env, chips in UNITS:
        path = os.path.join(out_dir, f"{app}-{tier}-deploy.yaml")
        with open(path, "w") as f:
            f.write(render_unit(app, model, tier, env, chips))
        print("wrote", path)
        jpath = os.path.join(jobs_dir, f"compile-{app}-{tier}-job.yaml")
        with open(jpath, "w") as f:
            f.write(render_job(app, model, tier, env, chips))
        print("wrote", jpath)

    for name, model, model_id, hosts, cph, topo, mesh, extra in MH_UNITS:
        path = os.path.join(out_dir, f"{name}-tpu-deploy.yaml")
        with open(path, "w") as f:
            f.write(render_mh_unit(name, model, model_id, hosts, cph, topo,
                                   mesh, extra))
        print("wrote", path)

    derived = _load_derived()
    if derived is None:
        print("no deploy/derived_weights.json — scaledobjects/routes not "
              "regenerated (run scripts/derive_weights.py first)")
        return
    so_dir = os.path.join(os.path.dirname(__file__), "scaledobjects")
    ing_dir = os.path.join(os.path.dirname(__file__), "ingress")
    for app, data in sorted(derived["apps"].items()):
        for mode in ("weighted", "equal"):
            path = os.path.join(so_dir, f"{app}-scaledobject-{mode}-routing.yaml")
            with open(path, "w") as f:
                f.write(render_scaledobjects(app, data["units"], mode))
            print("wrote", path)
        path = os.path.join(ing_dir, f"{app}-weighted-routing-ing.yaml")
        with open(path, "w") as f:
            f.write(render_weighted_route(app, data["units"]))
        print("wrote", path)


if __name__ == "__main__":
    main()
