"""Per-pod idempotency cache: replayed/hedged duplicates return the
original result instead of re-executing.

The fleet's retry layer (``resilience.hedge`` + cova's hedged dispatch)
deliberately sends the same request twice — a hedge after the adaptive
p95 delay, a budgeted retry after a retryable failure, a migration
resume replayed by a nervous client. Each of those duplicates carries
the SAME ``X-SHAI-Idempotency-Key`` (cova mints one when the client
didn't), and this cache is what turns "executed twice" into "executed
once, answered twice":

- a duplicate arriving AFTER the original completed replays the cached
  result — no admission, no engine work, and critically **no second
  tenant-ledger charge** (``serve.app`` returns before ``_InferScope``);
- a duplicate arriving WHILE the original is in flight *joins* it: the
  joiner parks on the entry's event and wakes with the original's
  result;
- a key is only ever associated with one execution at a time — failures
  are **not** cached (``fail`` clears the entry), because a retry after
  a real failure is exactly the case that SHOULD re-execute.

Keyed replay is opt-in per request (no header -> the cache is never
consulted; the PR-3 contract that non-idempotent replay stays forbidden
without a key is preserved by construction). The cache is bounded
(``SHAI_IDEMP_CACHE`` entries, ``SHAI_IDEMP_TTL_S`` freshness) and
pod-local: a hedge that lands on a *different* pod executes there — the
dedup story for cross-pod duplicates is first-winner-cancels at cova
plus this cache absorbing same-pod replays and duplicate migration
resumes.

Exported counters (``/stats`` -> ``"idempotency"`` and the Prometheus
families below; ``scripts/check_metrics_docs.py`` scans them here):
``shai_idemp_replayed_total`` (completed-entry replays),
``shai_idemp_joined_total`` (in-flight joins),
``shai_idemp_misses_total`` (new keys — executions),
``shai_idemp_evicted_total`` (bound/TTL evictions),
``shai_idemp_lookup_errors_total`` (injected/real lookup failures that
degraded to a miss), and the ``shai_idemp_entries`` gauge.

Chaos site :data:`resilience.faults.IDEMP_LOOKUP`: an injected error
makes :meth:`IdempotencyCache.begin` report a MISS — at-most-once
degrades to at-least-once, never to a dropped request.

Threading: lane threads (every keyed request) and scrape threads all
touch the table — every mutation moves under ``_lock`` (declared HOT in
``analysis/contract.py``: nothing blocking runs under it; joiners wait
on their entry's event strictly OUTSIDE the lock).
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from . import faults

#: header key (lowercased — serve.asgi lowercases all request headers)
IDEMP_HEADER = "x-shai-idempotency-key"

#: key grammar: printable, shell/log-safe, bounded — a client key that
#: fails this is a 400, never a silent pass-through (\Z, not $: $ would
#: let a trailing newline through)
_KEY_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,128}\Z")


def valid_key(key: str) -> bool:
    return bool(_KEY_RE.match(key or ""))


class _Entry:
    """One key's lifecycle: inflight (event unset) -> done | cleared."""

    __slots__ = ("state", "result", "event", "done_at")

    def __init__(self):
        self.state = "inflight"
        self.result: Optional[Dict[str, Any]] = None
        self.event = threading.Event()
        self.done_at = 0.0


class IdempotencyCache:
    """Bounded, TTL'd key -> completed-result table with in-flight join."""

    def __init__(self, max_entries: int = 1024, ttl_s: float = 600.0,
                 clock=time.monotonic):
        self.max_entries = max(1, int(max_entries))
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._counts: Dict[str, int] = {
            "replayed": 0, "joined": 0, "misses": 0, "evicted": 0,
            "lookup_errors": 0,
        }

    # -- lifecycle ---------------------------------------------------------

    def begin(self, key: str) -> Tuple[str, Optional[_Entry]]:
        """Claim or join ``key``. Returns one of:

        - ``("new", entry)`` — this caller owns the execution and must
          end it with :meth:`complete` or :meth:`fail`;
        - ``("done", entry)`` — a fresh completed result is cached
          (``entry.result``); replay it;
        - ``("inflight", entry)`` — the original is still executing;
          park on ``entry.event`` (OUTSIDE any lock) and re-read
          ``entry.state``/``entry.result`` after it sets.
        """
        # delay-kind faults at this site are applied by the (async) caller
        # via asleep_at — a blocking sleep here would stall the event loop
        inj = faults.get()
        if inj.should_fail(faults.IDEMP_LOOKUP):
            # degraded lookup: report a miss WITHOUT touching the table —
            # the request executes (at-least-once), and completion lands
            # through complete()'s upsert as usual
            with self._lock:
                self._counts["lookup_errors"] += 1
            return "new", _Entry()
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            e = self._entries.get(key)
            if e is not None:
                if e.state == "done":
                    self._entries.move_to_end(key)
                    self._counts["replayed"] += 1
                    return "done", e
                self._counts["joined"] += 1
                return "inflight", e
            e = _Entry()
            self._entries[key] = e
            self._counts["misses"] += 1
            self._evict_locked()
            return "new", e

    def complete(self, key: str, result: Dict[str, Any]) -> None:
        """Publish ``key``'s result and wake joiners. Upserts — a
        degraded-lookup execution still lands its completion."""
        now = self._clock()
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = _Entry()
                self._entries[key] = e
                self._evict_locked()
            e.state = "done"
            e.result = result
            e.done_at = now
            self._entries.move_to_end(key)
        e.event.set()

    def fail(self, key: str) -> None:
        """The execution failed: clear the claim so a later retry
        legitimately re-executes, and wake joiners (they re-read the
        entry, see ``state != "done"``, and run their own attempt)."""
        with self._lock:
            e = self._entries.pop(key, None)
        if e is not None:
            e.state = "failed"
            e.event.set()

    # -- bounds ------------------------------------------------------------

    def _purge_locked(self, now: float) -> None:
        """TTL sweep over completed entries (in-flight entries never
        expire here — their owner ends them)."""
        if self.ttl_s <= 0:
            return
        # shai-lint: allow(guarded-read) caller-holds-lock helper: every
        # caller (begin) runs this inside `with self._lock`
        stale = [k for k, e in self._entries.items()
                 if e.state == "done" and now - e.done_at > self.ttl_s]
        for k in stale:
            del self._entries[k]
        # shai-lint: allow(thread) caller-holds-lock helper (above)
        self._counts["evicted"] += len(stale)

    def _evict_locked(self) -> None:
        """Bound the table: oldest DONE entries go first; if the table is
        somehow all in-flight, the oldest claim goes anyway — bounded
        memory beats perfect dedup (the evicted duplicate re-executes)."""
        # shai-lint: allow(guarded-read) caller-holds-lock helper: every
        # caller (begin/complete) runs this inside `with self._lock`
        while len(self._entries) > self.max_entries:
            # shai-lint: allow(guarded-read) caller-holds-lock helper (above)
            victim = next((k for k, e in self._entries.items()
                           if e.state == "done"),
                          next(iter(self._entries)))
            # shai-lint: allow(thread) caller-holds-lock helper (above)
            e = self._entries.pop(victim)
            # shai-lint: allow(thread) caller-holds-lock helper (above)
            self._counts["evicted"] += 1
            if e.state != "done":
                e.state = "failed"
                e.event.set()

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = {f"{k}_total": float(v) for k, v in self._counts.items()}
            out["entries"] = float(len(self._entries))
            return out
