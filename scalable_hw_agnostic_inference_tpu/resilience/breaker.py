"""Per-backend circuit breaker: stop hammering a dead service.

Used by the cova fan-out client (``orchestrate.cova.CovaClient``): each
named backend gets its own breaker. Consecutive connect-phase failures
open the circuit; while open, calls fail fast (503 + ``Retry-After``)
instead of eating a connect timeout each. After a jittered exponential
backoff one probe is allowed through (half-open); success closes the
circuit, failure re-opens it with a longer backoff.

Jitter matters at fleet scale: without it, every orchestrator replica
probes a recovering backend at the same instant and re-kills it. The rng
is injectable so tests are deterministic.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Classic three-state breaker; thread-safe (the cova app serves
    concurrent fan-outs on one event loop plus test threads)."""

    def __init__(self, failure_threshold: int = 3,
                 base_backoff_s: float = 0.5, max_backoff_s: float = 30.0,
                 jitter_frac: float = 0.25,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter_frac = jitter_frac
        self._rng = rng or random.Random()
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_count = 0        # consecutive opens: escalates backoff
        self._open_until = 0.0
        self._probing = False       # one half-open probe at a time

    # -- state -------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        if self._state == OPEN and self._clock() >= self._open_until:
            return HALF_OPEN
        return self._state

    @property
    def retry_after_s(self) -> float:
        """Seconds until the next probe is allowed (0 when closed)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())

    def backoff_s(self, n_open: int) -> float:
        """Deterministic part of the n-th consecutive open's backoff."""
        return min(self.max_backoff_s,
                   self.base_backoff_s * (2 ** max(0, n_open - 1)))

    # -- transitions -------------------------------------------------------

    def allow(self) -> bool:
        """May a call proceed right now? While half-open, exactly one
        caller gets True (the probe) until it reports back."""
        with self._lock:
            st = self._effective_state()
            if st == CLOSED:
                return True
            if st == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def release_probe(self) -> None:
        """Release the half-open probe slot WITHOUT recording an outcome —
        for a probe that never reports back (e.g. the awaiting task was
        cancelled mid-call). Idempotent; without this the breaker would
        deadlock with ``allow()`` False forever, failing the backend long
        after it recovered."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._consecutive_failures = 0
            self._open_count = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            self._consecutive_failures += 1
            was_half_open = self._effective_state() == HALF_OPEN
            if (self._consecutive_failures >= self.failure_threshold
                    or was_half_open):
                self._open_count += 1
                base = self.backoff_s(self._open_count)
                jitter = 1.0 + self.jitter_frac * self._rng.random()
                self._open_until = self._clock() + base * jitter
                self._state = OPEN
