"""Deterministic fault injection: named sites, seeded draws, env/endpoint
driven.

The chaos suite's core claim — *every accepted request reaches a terminal
state within its deadline under any injected fault* — is only testable if
the faults themselves are reproducible. So the injector is seeded: each
site draws from its own ``random.Random(f"{seed}:{site}")`` stream, making
a site's firing pattern a pure function of (spec, seed, per-site call
count) regardless of how other sites interleave.

Spec grammar (``SHAI_FAULTS`` env var, or ``POST /debug/faults``)::

    spec    := clause ("," clause)*
    clause  := site "=" kind ["(" arg ")"] ["@" prob] ["#" limit]
    kind    := "delay" | "stall" | "error" | "drop"

- ``delay(seconds)`` — sleep before the site's work (step latency);
- ``stall(seconds)`` — same mechanism, spelled for long hangs (watchdog
  fodder); default 30 s when the arg is omitted;
- ``error`` — raise (``FaultError`` or the site's native exception type);
- ``drop`` — the site discards its message/effect (multihost mirror);
- ``@prob`` — firing probability per draw, default 1.0;
- ``#limit`` — max total firings, default unlimited.

Examples::

    SHAI_FAULTS="engine.step=delay(0.05)@0.5"        # flaky slow steps
    SHAI_FAULTS="engine.kv_reserve=error@0.3,cova.rpc=error#3"
    SHAI_FAULTS="engine.step=stall(20)#1"            # one 20s stall

Sites threaded through the stack (grep for the constant):

- :data:`ENGINE_STEP` — ``LLMEngine.step`` entry (latency/stall/crash);
- :data:`KV_RESERVE` — the admission reservation gate (``_try_reserve``):
  an injected failure reads as a dry pool, exercising reject/wait paths;
- :data:`COMPILE` — executable-factory cache miss (``_prefill_for`` etc.):
  an injected error is a compile failure;
- :data:`COVA_RPC` — cova fan-out client per-call (error -> connect error,
  delay -> added RPC latency);
- :data:`MIRROR` — multihost leader broadcast (drop -> mirror message
  lost);
- :data:`KVNET_FETCH` — the network KV transport's peer fetch
  (``kvnet.client``): error -> injected connect failure (the decode pod
  must degrade to recompute, never fail the request), delay -> added
  transfer latency;
- :data:`MIGRATE_SHIP` — the live-migration ship (``kvnet.migrate``):
  error -> the MIGRATE POST never leaves the pod, forcing the ladder
  down to the cold-replay rung (the client/cova replays against a peer
  without a resume handle), delay -> added ship latency;
- :data:`MIGRATE_RESTORE` — the receiving pod's KV restore
  (``kvnet.migrate.publish_entries``): error -> the migrated blocks are
  refused, forcing the warm-resume rung down to recompute-on-peer (the
  manifest is still accepted; the resumed request re-prefills).
- :data:`SCALE_DECIDE` — the fleet autoscaler's decision kernel
  (``orchestrate.scaler``): error -> the tick emits a deliberately WRONG
  decision (a spurious max-step scale-up) instead of the computed one —
  the control discipline (hysteresis, cool-downs, herd cap) must absorb
  it and re-converge on subsequent ticks;
- :data:`SCALE_APPLY` — the autoscaler's apply step: error -> the
  decision is made but never lands (a failed kubectl / actuator RPC);
  the controller must NOT commit its cool-down state and must retry the
  same decision next tick instead of wedging.
- :data:`HEDGE_FIRE` — cova's hedged-dispatch rung (``resilience.hedge``
  via ``orchestrate.cova``): delay -> added latency between "the primary
  looks slow" and the hedge actually launching, error -> the hedge is
  suppressed (the primary must still win or fail on its own) — so chaos
  tests drive BOTH the hedge-fired and hedge-denied paths
  deterministically;
- :data:`IDEMP_LOOKUP` — the per-pod idempotency-cache lookup
  (``resilience.idempotency``): error -> the lookup degrades to a cache
  MISS (the request executes; at-most-once degrades to at-least-once,
  never to a dropped request), delay -> a slow lookup;
- :data:`POISON_MARK` — the poison-registry mark after an abnormal
  engine death (``resilience.hedge.PoisonRegistry``): error -> the mark
  is lost (the quarantine needs one more abnormal attempt), so tests
  prove the K-threshold counts MARKS, not attempts.

The module-level injector is built once from ``SHAI_FAULTS`` /
``SHAI_FAULTS_SEED`` and replaced at runtime via :func:`configure` (the
``/debug/faults`` endpoint). With no spec, every helper is a dict-miss —
safe on the engine hot path.
"""

from __future__ import annotations

import logging
import random
import re
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

ENGINE_STEP = "engine.step"
KV_RESERVE = "engine.kv_reserve"
COMPILE = "engine.compile"
COVA_RPC = "cova.rpc"
MIRROR = "multihost.mirror"
KVNET_FETCH = "kvnet.fetch"
MIGRATE_SHIP = "migrate.ship"
MIGRATE_RESTORE = "migrate.restore"
# the KV-fabric peer-probe rung (kvnet.directory.FabricProbe): error ->
# the probed holder looks dead (breaker-counted), the admission ladder
# degrades to recompute — never a request failure
KVFABRIC_PROBE = "kvfabric.probe"
# the fleet autoscaler (orchestrate.scaler): decide -> a corrupted
# decision the control discipline must absorb; apply -> the actuator
# fails and the tick must retry, not wedge
SCALE_DECIDE = "scale.decide"
SCALE_APPLY = "scale.apply"
# request reliability (resilience.hedge / resilience.idempotency via
# orchestrate.cova and serve.app): hedge launch, per-pod idempotency
# lookup, and the poison-registry mark after an abnormal engine death
HEDGE_FIRE = "hedge.fire"
IDEMP_LOOKUP = "idemp.lookup"
POISON_MARK = "poison.mark"

KINDS = ("delay", "stall", "error", "drop")

_CLAUSE = re.compile(
    r"^(?P<site>[\w.\-]+)=(?P<kind>\w+)"
    r"(?:\((?P<arg>[^)]*)\))?"
    r"(?:@(?P<prob>[0-9.]+))?"
    r"(?:#(?P<limit>\d+))?$")


class FaultError(RuntimeError):
    """Default exception an ``error``-kind fault raises at its site."""


class _Clause:
    def __init__(self, site: str, kind: str, arg: float, prob: float,
                 limit: Optional[int], seed: int):
        self.site = site
        self.kind = kind
        self.arg = arg
        self.prob = prob
        self.limit = limit
        self.fired = 0
        self.draws = 0
        # per-clause stream: a site's firing pattern depends only on its
        # own draw count, never on other sites' call interleaving
        self._rng = random.Random(f"{seed}:{site}:{kind}")

    def draw(self) -> bool:
        """One deterministic firing decision (caller holds the lock)."""
        self.draws += 1
        if self.limit is not None and self.fired >= self.limit:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def describe(self) -> Dict:
        return {"site": self.site, "kind": self.kind, "arg": self.arg,
                "prob": self.prob, "limit": self.limit,
                "fired": self.fired, "draws": self.draws}


def _parse(spec: str, seed: int) -> Dict[str, List[_Clause]]:
    out: Dict[str, List[_Clause]] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE.match(raw)
        if not m:
            raise ValueError(f"bad fault clause {raw!r} "
                             f"(grammar: site=kind[(arg)][@prob][#limit])")
        kind = m.group("kind")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r} "
                             f"(known: {KINDS})")
        arg = m.group("arg")
        if arg:
            arg_f = float(arg)
        else:
            arg_f = 30.0 if kind == "stall" else 0.0
        prob = float(m.group("prob")) if m.group("prob") else 1.0
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob out of [0,1] in {raw!r}")
        limit = int(m.group("limit")) if m.group("limit") else None
        site = m.group("site")
        out.setdefault(site, []).append(
            _Clause(site, kind, arg_f, prob, limit, seed))
    return out


class FaultInjector:
    """Seeded fault schedule over named sites. Thread-safe: sites fire from
    the engine loop, the event loop, and pool threads concurrently."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._clauses = _parse(spec, seed)
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self._clauses)

    def _fire(self, site: str, kinds) -> Optional[_Clause]:
        clauses = self._clauses.get(site)
        if not clauses:
            return None
        with self._lock:
            for c in clauses:
                if c.kind in kinds and c.draw():
                    return c
        return None

    # -- site helpers (each consults only its own kinds) -------------------

    def _sleep_seconds(self, site: str) -> float:
        c = self._fire(site, ("delay", "stall"))
        if c is None:
            return 0.0
        log.warning("fault %s: %s %.3fs", site, c.kind, c.arg)
        return c.arg

    def sleep_at(self, site: str) -> float:
        """Apply a ``delay``/``stall`` clause; returns seconds slept.
        Blocking — for thread-resident sites (the engine loop)."""
        s = self._sleep_seconds(site)
        if s:
            time.sleep(s)
        return s

    async def asleep_at(self, site: str) -> float:
        """:meth:`sleep_at` for event-loop-resident sites (cova's fan-out):
        awaits instead of blocking, so an injected RPC delay slows THAT
        call, not every coroutine in the process. Same draw stream."""
        s = self._sleep_seconds(site)
        if s:
            import asyncio

            await asyncio.sleep(s)
        return s

    def should_fail(self, site: str) -> bool:
        """True when an ``error`` clause fires — the site raises its own
        native failure (or calls :meth:`raise_at`)."""
        c = self._fire(site, ("error",))
        if c is not None:
            log.warning("fault %s: injected error", site)
            return True
        return False

    def raise_at(self, site: str, exc=FaultError) -> None:
        if self.should_fail(site):
            raise exc(f"injected fault at {site}")

    def should_drop(self, site: str) -> bool:
        c = self._fire(site, ("drop",))
        if c is not None:
            log.warning("fault %s: dropping", site)
            return True
        return False

    def snapshot(self) -> Dict:
        """Introspection payload for ``GET /debug/faults``."""
        with self._lock:
            return {"spec": self.spec, "seed": self.seed,
                    "active": self.active,
                    "clauses": [c.describe()
                                for cl in self._clauses.values()
                                for c in cl]}


_NOOP = FaultInjector("", 0)
_global: Optional[FaultInjector] = None
_global_lock = threading.Lock()


def get() -> FaultInjector:
    """The process injector: built once from ``SHAI_FAULTS`` (seed
    ``SHAI_FAULTS_SEED``, default 0), no-op when unset. Cheap when idle —
    the hot path pays one attribute read and a dict miss."""
    global _global
    inj = _global
    if inj is not None:
        return inj
    with _global_lock:
        if _global is None:
            from ..obs.util import env_int, env_str

            spec = env_str("SHAI_FAULTS")
            seed = env_int("SHAI_FAULTS_SEED", 0)
            try:
                _global = FaultInjector(spec, seed) if spec else _NOOP
            except ValueError:
                log.exception("bad SHAI_FAULTS spec %r — faults disabled",
                              spec)
                _global = _NOOP
        return _global


def configure(spec: str, seed: int = 0) -> FaultInjector:
    """Replace the process injector (the ``POST /debug/faults`` path).
    Raises ``ValueError`` on a bad spec, leaving the old schedule live."""
    global _global
    inj = FaultInjector(spec, seed) if spec else _NOOP
    with _global_lock:
        _global = inj
    return inj


def endpoint_enabled() -> bool:
    """``POST /debug/faults`` is armed only by explicit env opt-in — a
    production pod must not accept fault writes from anyone who can reach
    its port. ``SHAI_FAULTS`` alone does NOT arm it: a canary running a
    benign env fault must not open an unauthenticated write endpoint."""
    from ..obs.util import env_flag

    return bool(env_flag("SHAI_FAULTS_ENDPOINT", False))


def reset() -> None:
    """Drop back to the env-derived schedule (tests)."""
    global _global
    with _global_lock:
        _global = None
