"""Multi-tenant QoS: priority classes, weighted-fair scheduling, budgets.

Millions of users means contention, and a single global admission gate lets
one greedy client park the whole ``waiting`` deque, exhaust the KV pool,
and starve everyone else. This module holds the three pieces that make
every contention decision class-aware, stdlib-only so both the serving
layer and the engine can import it:

- **Priority classes** (``X-SHAI-Priority`` header -> ``Request.priority``):
  three classes, ``high``/``normal``/``low`` (0/1/2 — LOWER is more
  important). Lenient parse: an unrecognized value degrades to the env
  default (``SHAI_PRIORITY_DEFAULT``), never a 400 — a typo'd priority
  header must not fail the request it was trying to prioritize.
- **WeightedFairScheduler**: a stride scheduler over the priority classes
  with anti-starvation aging. Each class consumes ``STRIDE/weight`` pass
  units per pick, the lowest pass value is served next, so over N rounds
  class k receives ~``weight_k / sum(weights)`` of the picks — FIFO within
  a class, and low priority is *delayed, never starved*: a class skipped
  ``aging_rounds`` consecutive rounds while eligible is served immediately,
  whatever the weights say. The engine rotates the selected class's oldest
  request to the queue head (:func:`schedule_rotate`), so every existing
  ``popleft`` admission path dequeues weighted-fair without changing its
  mechanics — and with ``SHAI_QOS`` unset the rotation never runs, keeping
  the QoS-off engine token-exact vs the FIFO baseline.
- **TenantLedger** (``X-SHAI-Tenant`` header): per-tenant token-rate
  budgets (token-bucket refill, ``SHAI_TENANT_BUDGETS`` grammar) plus
  per-tenant inflight accounting. Enforcement is *charge actuals, gate on
  debt*: a completed request debits its real token count (prompt +
  generated — the numbers exist only after the fact), and admission is
  refused while the bucket is in debt, with a ``Retry-After`` derived from
  the refill deficit (``resilience.admission`` maps it to 429). Bounded
  cardinality: at most ``SHAI_QOS_MAX_TENANTS`` distinct tenants are
  tracked; overflow tenants collapse into ``"other"`` so an adversary
  minting tenant names cannot grow the ledger (or the metric label set)
  without bound.
"""

from __future__ import annotations

import contextvars
import dataclasses
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..obs.util import env_flag, env_int, env_str
import logging

log = logging.getLogger(__name__)

#: request headers naming the tenant and priority class
TENANT_HEADER = "x-shai-tenant"
PRIORITY_HEADER = "x-shai-priority"

#: priority classes — LOWER is more important (sorts naturally)
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2
PRIORITY_NAMES = {"high": PRIORITY_HIGH, "normal": PRIORITY_NORMAL,
                  "low": PRIORITY_LOW}
_CLASS_NAMES = {v: k for k, v in PRIORITY_NAMES.items()}

#: default stride weights per class: high gets 8x low's service share
DEFAULT_WEIGHTS = {PRIORITY_HIGH: 8.0, PRIORITY_NORMAL: 4.0,
                   PRIORITY_LOW: 1.0}

#: tenant label charset/length bound — anything else sanitizes away so a
#: hostile header cannot mint unbounded or exposition-breaking label values
_TENANT_RE = re.compile(r"[^A-Za-z0-9_.:-]+")
MAX_TENANT_CHARS = 64

#: the bounded-cardinality overflow bucket (metrics label + ledger key)
OTHER_TENANT = "other"
#: the label requests without a tenant header account under
DEFAULT_TENANT = "default"


def qos_enabled() -> bool:
    """``SHAI_QOS`` gate, default OFF: with it unset the engine's dequeue,
    and therefore its token stream, is byte-identical to the FIFO
    baseline (the differential contract ``tests/test_qos.py`` holds)."""
    return bool(env_flag("SHAI_QOS", False))


def sanitize_tenant(raw: Optional[str]) -> str:
    """Bounded, charset-safe tenant id ('' when absent/empty)."""
    if not raw:
        return ""
    return _TENANT_RE.sub("", str(raw))[:MAX_TENANT_CHARS]


def parse_priority(raw: Optional[str],
                   default: int = PRIORITY_NORMAL) -> int:
    """Lenient priority parse: ``high``/``normal``/``low`` or ``0``/``1``/
    ``2``; anything else (absent, typo) degrades to ``default`` — a
    malformed QoS hint must never fail the request carrying it."""
    if raw is None:
        return default
    v = str(raw).strip().lower()
    if v in PRIORITY_NAMES:
        return PRIORITY_NAMES[v]
    try:
        n = int(v)
    except ValueError:
        return default
    return min(max(n, PRIORITY_HIGH), PRIORITY_LOW)


def class_name(priority: int) -> str:
    return _CLASS_NAMES.get(priority, str(priority))


def qos_from_headers(headers: Dict[str, str]) -> Tuple[str, int]:
    """Resolve ``(tenant, priority)`` for one request: header wins, env
    default (``SHAI_TENANT_DEFAULT`` / ``SHAI_PRIORITY_DEFAULT``) fills
    in. Both parses are lenient by contract."""
    tenant = sanitize_tenant(headers.get(TENANT_HEADER))
    if not tenant:
        tenant = sanitize_tenant(env_str("SHAI_TENANT_DEFAULT", ""))
    default_prio = parse_priority(env_str("SHAI_PRIORITY_DEFAULT", ""),
                                  PRIORITY_NORMAL)
    return tenant, parse_priority(headers.get(PRIORITY_HEADER),
                                  default_prio)


# -- contextvar propagation (the deadline pattern) ---------------------------

@dataclasses.dataclass(frozen=True)
class QosTag:
    """One request's QoS identity, riding the request context onto the
    model lane (``serve.app._run_model`` copies the context) and from
    there into ``EngineLoop.submit``."""

    tenant: str = ""
    priority: int = PRIORITY_NORMAL


_current: "contextvars.ContextVar[Optional[QosTag]]" = (
    contextvars.ContextVar("shai_qos", default=None))


def set_current_qos(tag: Optional[QosTag]) -> "contextvars.Token":
    return _current.set(tag)


def reset_current_qos(token: "contextvars.Token") -> None:
    _current.reset(token)


def current_qos() -> Optional[QosTag]:
    return _current.get()


# -- weighted-fair scheduler kernel ------------------------------------------

class WeightedFairScheduler:
    """Stride scheduling over priority classes, with aging.

    Pure host arithmetic, no clock, no allocation per pick beyond dict
    entries for classes actually seen — safe to call on the engine's
    admission path every step. Single-threaded by contract: only the
    engine-loop thread calls :meth:`select` (the snapshot readout copies
    under no lock because the GIL makes the dict reads atomic and the
    numbers are diagnostics, not invariants).

    Semantics:

    - each class ``c`` holds a ``pass`` value; :meth:`select` returns the
      eligible class with the minimum pass (ties -> more important class)
      and advances its pass by ``STRIDE / weight[c]``;
    - a class joining (or re-joining after its queue drained) enters at
      the current eligible minimum, so absence never banks credit;
    - **aging**: a class skipped ``aging_rounds`` consecutive selections
      while eligible is served immediately — the starvation-freedom bound
      property-tested in ``tests/test_qos.py`` (whatever weights an
      operator configures, max delay is ``aging_rounds`` rounds).
    """

    STRIDE = float(1 << 20)

    def __init__(self, weights: Optional[Dict[int, float]] = None,
                 aging_rounds: int = 32):
        w = dict(DEFAULT_WEIGHTS)
        if weights:
            w.update(weights)
        #: class -> stride weight (floor 1.0: a zero/negative weight would
        #: be starvation by configuration, exactly what aging exists to
        #: prevent)
        self.weights = {int(c): max(1.0, float(v)) for c, v in w.items()}
        self.aging_rounds = max(1, int(aging_rounds))
        self._pass: Dict[int, float] = {}
        self._skipped: Dict[int, int] = {}
        self.picks: Dict[int, int] = {}
        self.aged_picks = 0

    @classmethod
    def from_env(cls) -> "WeightedFairScheduler":
        """``SHAI_QOS_WEIGHTS`` (``high=8,normal=4,low=1`` — names or
        class numbers, lenient per clause) + ``SHAI_QOS_AGING_ROUNDS``."""
        weights: Dict[int, float] = {}
        spec = env_str("SHAI_QOS_WEIGHTS", "")
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            name, sep, val = clause.partition("=")
            try:
                if not sep:
                    raise ValueError("missing '='")
                cls_id = parse_priority(name, -1)
                if cls_id < 0:
                    raise ValueError(f"unknown class {name!r}")
                weights[cls_id] = float(val)
            except ValueError as e:
                log.warning("malformed SHAI_QOS_WEIGHTS clause %r (%s) — "
                            "ignored", clause, e)
        return cls(weights or None,
                   aging_rounds=env_int("SHAI_QOS_AGING_ROUNDS", 32))

    def _stride(self, cls_id: int) -> float:
        return self.STRIDE / self.weights.get(cls_id, 1.0)

    def select(self, nonempty: Sequence[int]) -> int:
        """Pick the next class to serve among ``nonempty`` (class ids with
        queued work). Advances the stride/aging state."""
        eligible = sorted(set(nonempty))
        if not eligible:
            raise ValueError("select() needs at least one non-empty class")
        known = [self._pass[c] for c in eligible if c in self._pass]
        floor = min(known) if known else 0.0
        for c in eligible:
            # a class whose queue just became non-empty (or that was never
            # seen) joins at the eligible minimum: absence banks no credit
            self._pass[c] = max(self._pass.get(c, floor), floor)
        for c in self._skipped:
            # ...and the same for the AGING counter: "skipped" means
            # skipped while eligible — a drained class re-joining must
            # not carry its old streak into an immediate forced pick
            if c not in eligible:
                self._skipped[c] = 0
        aged = [c for c in eligible
                if self._skipped.get(c, 0) >= self.aging_rounds]
        if aged:
            pick = max(aged, key=lambda c: (self._skipped.get(c, 0), c))
            self.aged_picks += 1
        else:
            pick = min(eligible, key=lambda c: (self._pass[c], c))
        self._pass[pick] += self._stride(pick)
        for c in eligible:
            self._skipped[c] = 0 if c == pick else self._skipped.get(c, 0) + 1
        self.picks[pick] = self.picks.get(pick, 0) + 1
        # rebase so pass values stay bounded over process lifetime
        base = min(self._pass.values())
        if base > 1e15:
            for c in self._pass:
                self._pass[c] -= base
        return pick

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {"aged_picks": float(self.aged_picks)}
        for c, n in sorted(self.picks.items()):
            out[f"picks_{class_name(c)}"] = float(n)
        for c, w in sorted(self.weights.items()):
            out[f"weight_{class_name(c)}"] = float(w)
        return out


def schedule_rotate(waiting: "deque", sched: WeightedFairScheduler) -> None:
    """THE weighted-fair dequeue: rotate the scheduler-selected class's
    OLDEST request to the head of ``waiting`` so the engine's existing
    ``popleft`` admission ladder dequeues it next. FIFO within a class by
    construction (the first index of the picked class moves); a no-op when
    fewer than two classes are queued — which also makes the uniform-
    priority QoS-on run token-exact vs QoS-off (the stride state never
    advances without real contention). Shared verbatim by the engine and
    the deviceless property tests in ``tests/test_qos.py``."""
    if len(waiting) < 2:
        return
    first_idx: Dict[int, int] = {}
    for idx, r in enumerate(waiting):
        p = getattr(r, "priority", PRIORITY_NORMAL)
        if p not in first_idx:
            first_idx[p] = idx
    if len(first_idx) < 2:
        return
    idx = first_idx[sched.select(sorted(first_idx))]
    if idx:
        req = waiting[idx]
        del waiting[idx]
        waiting.appendleft(req)


# -- per-tenant budgets ------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TenantBudget:
    """Token-rate budget: ``rate`` tokens/second refill up to ``burst``."""

    rate: float
    burst: float


def parse_budgets(spec: str) -> Dict[str, TenantBudget]:
    """``SHAI_TENANT_BUDGETS`` grammar: ``name=rate[:burst],...`` —
    ``rate`` in tokens/second, ``burst`` the bucket capacity (default
    ``max(rate, 1)``); ``*`` names the default budget applied to every
    tenant without its own clause (tenants with no clause and no ``*``
    are unmetered). Lenient per clause: a malformed clause warns and is
    skipped — one typo must not strip (or impose) every budget."""
    out: Dict[str, TenantBudget] = {}
    for clause in (spec or "").split(","):
        clause = clause.strip()
        if not clause:
            continue
        name, sep, val = clause.partition("=")
        name = name.strip()
        try:
            if not sep or not name:
                raise ValueError("expected name=rate[:burst]")
            rate_s, _, burst_s = val.partition(":")
            rate = float(rate_s)
            burst = float(burst_s) if burst_s else max(rate, 1.0)
            if rate <= 0 or burst <= 0:
                raise ValueError("rate and burst must be > 0")
            key = name if name == "*" else sanitize_tenant(name)
            if not key:
                raise ValueError("empty tenant name")
            out[key] = TenantBudget(rate=rate, burst=burst)
        except ValueError as e:
            log.warning("malformed SHAI_TENANT_BUDGETS clause %r (%s) — "
                        "ignored", clause, e)
    return out


class TenantLedger:
    """Per-tenant token buckets + inflight accounting, thread-safe.

    Written from every serving thread (admission checks, completion
    charges), read by the scrape/stats threads — every counter mutation
    moves under ``_lock`` (shai-lint ``ClassPolicy``).

    Budget semantics (*charge actuals, gate on debt*): each tenant's
    bucket starts full at ``burst`` and refills at ``rate`` tokens/s;
    :meth:`charge` debits a completed request's real token count and may
    drive the balance negative (the request was already served — the debt
    is what gates the NEXT one); :meth:`admit` refuses while the balance
    is not positive, returning the refill time needed to climb back above
    zero — the budget-derived ``Retry-After``. Tenants without a budget
    (and no ``*`` default) are unmetered but still counted.
    """

    def __init__(self, budgets: Optional[Dict[str, TenantBudget]] = None,
                 max_tenants: int = 64,
                 clock: Callable[[], float] = time.monotonic):
        self.budgets = dict(budgets or {})
        self.default_budget = self.budgets.pop("*", None)
        self.max_tenants = max(1, int(max_tenants))
        self._clock = clock
        self._lock = threading.Lock()
        # tenant -> {"balance": float, "at": float} for budgeted tenants
        self._buckets: Dict[str, Dict[str, float]] = {}
        # tenant -> cumulative/live counters (one dict per tenant, bounded)
        self._stats: Dict[str, Dict[str, float]] = {}

    @classmethod
    def from_env(cls) -> "TenantLedger":
        return cls(parse_budgets(env_str("SHAI_TENANT_BUDGETS", "")),
                   max_tenants=env_int("SHAI_QOS_MAX_TENANTS", 64))

    @property
    def metered(self) -> bool:
        return bool(self.budgets or self.default_budget)

    def _key(self, tenant: str) -> str:
        """Bounded-cardinality accounting key (callers hold ``_lock``):
        a tenant never seen before lands in ``other`` once the table is
        full — unless it carries its OWN configured budget, which must
        stay enforceable no matter how many anonymous tenants showed up."""
        t = sanitize_tenant(tenant) or DEFAULT_TENANT
        # shai-lint: allow(guarded-read) caller-holds-lock helper: every
        # caller enters under `with self._lock`
        if t in self._stats or t in self.budgets:
            return t
        # shai-lint: allow(guarded-read) caller-holds-lock helper (above)
        if len(self._stats) >= self.max_tenants:
            return OTHER_TENANT
        return t

    def _budget_of(self, key: str) -> Optional[TenantBudget]:
        return self.budgets.get(key, self.default_budget)

    def _bucket(self, key: str, budget: TenantBudget,
                now: float) -> Dict[str, float]:
        """Refilled bucket state for ``key`` (callers hold ``_lock``)."""
        # shai-lint: allow(guarded-read) caller-holds-lock helper: every
        # caller (admit/charge/snapshot) enters under `with self._lock`
        b = self._buckets.get(key)
        if b is None:
            # shai-lint: allow(thread) caller-holds-lock helper: every
            # caller (admit/charge/snapshot) enters under `with self._lock`
            b = self._buckets[key] = {"balance": budget.burst, "at": now}
        else:
            b["balance"] = min(
                budget.burst,
                b["balance"] + (now - b["at"]) * budget.rate)
            b["at"] = now
        return b

    def _stat(self, key: str) -> Dict[str, float]:
        # shai-lint: allow(guarded-read) caller-holds-lock helper: every
        # caller (admit/charge/note_*/label_of) enters under
        # `with self._lock`
        s = self._stats.get(key)
        if s is None:
            # shai-lint: allow(thread) caller-holds-lock helper: every
            # caller (admit/charge/note_*/label_of) enters under
            # `with self._lock`
            s = self._stats[key] = {"requests": 0, "tokens": 0,
                                    "inflight": 0, "shed": 0}
        return s

    def admit(self, tenant: str) -> Optional[float]:
        """None = admit; a float = refuse, retry after this many seconds
        (the time the bucket needs to refill out of debt — finite by
        construction since every budget has ``rate > 0``)."""
        with self._lock:
            key = self._key(tenant)
            budget = self._budget_of(key)
            if budget is None:
                return None
            b = self._bucket(key, budget, self._clock())
            if b["balance"] > 0.0:
                return None
            self._stat(key)["shed"] += 1
            # climb from the current (possibly negative) balance back to
            # a positive bucket: deficit plus one token of headroom
            return max(0.1, (1.0 - b["balance"]) / budget.rate)

    def charge(self, tenant: str, tokens: int) -> None:
        """Debit a completed request's actual token usage (may drive the
        bucket into debt — served work is never clawed back, it just
        delays the tenant's next admission)."""
        if tokens <= 0:
            return
        with self._lock:
            key = self._key(tenant)
            self._stat(key)["tokens"] += int(tokens)
            budget = self._budget_of(key)
            if budget is not None:
                b = self._bucket(key, budget, self._clock())
                b["balance"] -= float(tokens)

    def note_start(self, tenant: str) -> None:
        with self._lock:
            s = self._stat(self._key(tenant))
            s["requests"] += 1
            s["inflight"] += 1

    def note_done(self, tenant: str) -> None:
        with self._lock:
            s = self._stat(self._key(tenant))
            s["inflight"] = max(0, s["inflight"] - 1)

    def label_of(self, tenant: str) -> str:
        """The bounded accounting/metric label for ``tenant`` — registers
        it (inside the cardinality cap) so a repeat offender keeps ONE
        stable label and a name-minting adversary collapses into
        ``other`` instead of growing the label set."""
        with self._lock:
            key = self._key(tenant)
            self._stat(key)
            return key

    def inflight_of(self, tenant: str) -> int:
        with self._lock:
            return int(self._stats.get(self._key(tenant), {})
                       .get("inflight", 0))

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant usage + live budget balances — the ``/stats`` ->
        ``qos.tenants`` payload and the ``shai_tenant_*`` gauge source."""
        with self._lock:
            now = self._clock()
            out: Dict[str, Dict[str, float]] = {}
            for key, s in self._stats.items():
                ent = dict(s)
                budget = self._budget_of(key)
                if budget is not None:
                    b = self._bucket(key, budget, now)
                    ent["budget_balance"] = round(b["balance"], 3)
                    ent["budget_rate"] = budget.rate
                out[key] = ent
            return out
