"""Request deadlines: the budget every request carries through the stack.

A request without a deadline can hang a client (and a serving thread, and
an engine slot) forever; the paper's failover controller only reroutes
FUTURE traffic. Here every request gets a deadline — from the
``X-SHAI-Deadline-Ms`` header, or the unit's ``DEADLINE_MS`` env default —
carried on a contextvar so it survives the hop from the event loop onto
the model lane thread (``serve.app._run_model`` copies the context). The
engine checks it every step and finishes expired requests with stop reason
``"timeout"``; the serving layer maps that to a 504.

Monotonic-clock based: a deadline is an absolute ``time.monotonic()``
instant, immune to wall-clock jumps, valid only within this process (the
header carries a *duration*, never an instant — clock skew between client
and pod cannot corrupt it).
"""

from __future__ import annotations

import contextvars
import dataclasses
import math
import time
from typing import Dict, Optional

#: request header naming the total budget in milliseconds
DEADLINE_HEADER = "x-shai-deadline-ms"

#: clamp: a deadline longer than this is a client bug, not a budget
MAX_DEADLINE_MS = 24 * 3600 * 1000


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute monotonic instant by which the request must be terminal."""

    at: float  # time.monotonic() instant

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + ms / 1e3)

    @property
    def remaining_s(self) -> float:
        return self.at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining_s <= 0.0


_current: "contextvars.ContextVar[Optional[Deadline]]" = (
    contextvars.ContextVar("shai_deadline", default=None))


def set_current_deadline(dl: Optional[Deadline]) -> "contextvars.Token":
    """Install the request's deadline on the context; returns the reset
    token (the serving layer resets it after the handler, so a keep-alive
    connection's next request can't inherit a stale budget)."""
    return _current.set(dl)


def reset_current_deadline(token: "contextvars.Token") -> None:
    _current.reset(token)


def current_deadline() -> Optional[Deadline]:
    return _current.get()


def deadline_from_headers(headers: Dict[str, str],
                          default_ms: float = 0.0) -> Optional[Deadline]:
    """Resolve a request's deadline: header wins, env default fills in,
    0/absent means no deadline. Raises ``ValueError`` on a malformed or
    non-positive header (a client error, mapped to a 400)."""
    raw = headers.get(DEADLINE_HEADER)
    if raw is None:
        return Deadline.after_ms(default_ms) if default_ms > 0 else None
    try:
        ms = float(raw)
    except ValueError:
        raise ValueError(
            f"{DEADLINE_HEADER} must be a number of milliseconds, "
            f"got {raw!r}")
    # isfinite: 'nan' slips through both `<= 0` and `min()` (every NaN
    # comparison is False), which would mint Deadline(at=NaN) — a request
    # that can never expire in the engine but instantly TimeoutErrors the
    # waiting lane thread, orphaning the decode
    if not math.isfinite(ms) or ms <= 0:
        raise ValueError(f"{DEADLINE_HEADER} must be a finite number > 0, "
                         f"got {raw!r}")
    return Deadline.after_ms(min(ms, MAX_DEADLINE_MS))
