"""Bounded admission: shed load at the HTTP door instead of parking threads.

The engine queue was previously unbounded — any number of requests could
pile into ``add_request`` while the pool was saturated, each one parking a
serving-lane thread on a future for up to 600 s. That converts overload
into latency collapse (every queued request times out together) instead of
the fast 429 a load balancer can act on.

The gate prices admission with the SAME thresholds the failover controller
uses (:class:`orchestrate.capacity_checker.OverloadThresholds`, via
``is_overloaded``): sustained admission-queue depth or a KV pool at the
preemption edge. One threshold owner means the pod starts shedding exactly
where the fleet controller would call it saturated — the 429s a client
sees and the failover the controller triggers describe the same line.

Shed responses carry ``Retry-After``; counts are exported as
``shai_shed_total{reason}`` on ``/metrics`` (see ``serve.metrics``) and
under ``/stats`` → ``"shed"``.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from ..orchestrate.capacity_checker import OverloadThresholds, is_overloaded
from .qos import TenantLedger


@dataclasses.dataclass(frozen=True)
class Shed:
    """One shed decision: HTTP status + reason + client backoff hint."""

    status: int          # 429 (overload) or 503 (draining)
    reason: str          # "draining" | "queue_depth" | "kv_pressure" | ...
    retry_after_s: float

    @property
    def detail(self) -> str:
        return {
            "draining": "pod is draining: shutting down, retry elsewhere",
            "queue_depth": "admission queue is full, retry later",
            "kv_pressure": "KV pool is at the preemption edge, retry later",
            "inflight": "too many requests in flight, retry later",
            "tenant_budget": "tenant token-rate budget exhausted, retry "
                             "after the bucket refills",
            "tenant_inflight": "tenant in-flight cap reached, retry later",
        }.get(self.reason, self.reason)

    @property
    def headers(self) -> Dict[str, str]:
        return {"retry-after": str(max(1, int(round(self.retry_after_s))))}


class AdmissionGate:
    """Engine-aware load shedding in front of ``add_request``.

    ``check`` reads the engine's obs telemetry snapshot (queue depth and KV
    utilization gauges — the numbers the autoscaler already scrapes) plus
    the drain flag, an optional in-flight cap, and the serving lane's width
    (so blocking requests queued in the lane executor — invisible to the
    engine's gauges — still count against the queue-depth threshold).
    Returns a :class:`Shed` to refuse, None to admit. Thread-safe counters.
    """

    def __init__(self, thresholds: Optional[OverloadThresholds] = None,
                 max_inflight: int = 0, retry_after_s: float = 1.0,
                 drain_retry_after_s: float = 5.0,
                 tier_full_utilization: float = 0.95,
                 tier_full_kv_utilization: float = 0.85,
                 ledger: Optional[TenantLedger] = None,
                 tenant_max_inflight: int = 0):
        self.thresholds = thresholds or OverloadThresholds()
        self.max_inflight = max_inflight  # 0 = no cap
        self.retry_after_s = retry_after_s
        self.drain_retry_after_s = drain_retry_after_s
        # multi-tenant QoS (resilience.qos): when a budget ledger is
        # attached, a tenant in token-bucket debt sheds with 429 +
        # a Retry-After DERIVED from its refill deficit (finite by
        # construction) instead of the static hint the structural sheds
        # keep; tenant_max_inflight optionally caps one tenant's
        # concurrency (0 = off) so a flooder can't own every lane slot
        # even inside its token budget.
        self.ledger = ledger
        self.tenant_max_inflight = tenant_max_inflight
        # host KV tier pricing (kvtier): while the host pool can absorb
        # demotions, device eviction is cheap (a copy, not lost work) and
        # the normal max_kv_utilization line applies. Once the HOST pool
        # saturates (>= tier_full_utilization), every further eviction
        # destroys banked prefill again — the gate tightens to the lower
        # tier_full_kv_utilization line so shedding starts BEFORE the pod
        # re-enters the recompute regime. Pods without a tier never report
        # host_kv_utilization and are unaffected.
        self.tier_full_utilization = tier_full_utilization
        self.tier_full_kv_utilization = tier_full_kv_utilization
        self._lock = threading.Lock()
        self._shed: Dict[str, int] = {}

    def check(self, engine_stats: Optional[dict] = None, inflight: int = 0,
              draining: bool = False, lane_width: int = 0,
              lane_pending: int = 0, tenant: str = "") -> Optional[Shed]:
        shed = self._decide(engine_stats, inflight, draining, lane_width,
                            lane_pending, tenant)
        if shed is not None:
            with self._lock:
                self._shed[shed.reason] = self._shed.get(shed.reason, 0) + 1
        return shed

    def _decide(self, stats: Optional[dict], inflight: int,
                draining: bool, lane_width: int,
                lane_pending: int, tenant: str = "") -> Optional[Shed]:
        if draining:
            return Shed(503, "draining", self.drain_retry_after_s)
        if self.ledger is not None:
            # per-tenant enforcement BEFORE the structural caps: an
            # over-budget tenant must shed even on an idle pod, and its
            # Retry-After is the bucket's actual refill time — the static
            # hint stays for the structural (non-budget) reasons below
            ra = self.ledger.admit(tenant)
            if ra is not None:
                return Shed(429, "tenant_budget", ra)
            if (self.tenant_max_inflight
                    and self.ledger.inflight_of(tenant)
                    >= self.tenant_max_inflight):
                return Shed(429, "tenant_inflight", self.retry_after_s)
        if self.max_inflight and inflight >= self.max_inflight:
            return Shed(429, "inflight", self.retry_after_s)
        # Lane backlog: blocking requests beyond the executor's width queue
        # INVISIBLY to the engine's "waiting" gauge (only `lane_width`
        # threads ever reach add_request at once), so price the app-level
        # overflow with the same queue-depth threshold. Without this, a
        # burst of blocking calls parks unboundedly in the lane queue and
        # overload becomes latency collapse with zero 429s. ``lane_pending``
        # counts only lane-bound requests — live SSE streams run on the
        # stream pool and must not read as executor queue depth (they are
        # still visible to ``inflight``/MAX_INFLIGHT above).
        if (lane_width > 0
                and lane_pending - lane_width > self.thresholds.max_queue_depth):
            return Shed(429, "queue_depth", self.retry_after_s)
        if (isinstance(stats, dict)
                and stats.get("host_kv_utilization", 0.0)
                >= self.tier_full_utilization
                and stats.get("kv_utilization", 0.0)
                > self.tier_full_kv_utilization):
            # saturated host tier: demotion degraded back to deletion, so
            # device-KV pressure is priced at the tighter line
            return Shed(429, "kv_pressure", self.retry_after_s)
        if isinstance(stats, dict) and is_overloaded(stats, self.thresholds):
            reason = ("queue_depth"
                      if stats.get("waiting", 0) > self.thresholds.max_queue_depth
                      else "kv_pressure")
            return Shed(429, reason, self.retry_after_s)
        return None

    @property
    def shed_total(self) -> int:
        with self._lock:
            return sum(self._shed.values())

    def shed_by_reason(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._shed)
