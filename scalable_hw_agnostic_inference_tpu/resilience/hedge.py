"""Fleet retry discipline: hedged dispatch, a token-bucket retry budget,
and poison-request quarantine (used by ``orchestrate.cova``).

Three classic fleet-killers, one module:

- **Tail amplification** — a single slow pod drags p99 for every request
  routed there. :class:`HedgeGovernor` tracks recent primary latencies
  and, once the primary attempt outlives the adaptive p95 delay, cova
  fires ONE hedge to the next-ranked healthy pod; first winner answers,
  the loser is cancelled.
- **Retry storms** — naive retries turn a brownout into an outage by
  multiplying offered load exactly when capacity dipped.
  :class:`RetryBudget` is a token bucket fed by *primary* traffic
  (``SHAI_RETRY_BUDGET_PCT`` tokens per primary attempt, default 0.1):
  every hedge and every retry spends one token, so fleet-wide attempt
  amplification is bounded at ``1 + pct`` (plus the small initial
  burst) no matter how degraded the fleet is — a starved budget sheds
  instead of self-amplifying.
- **Poison requests** — a request that crashes an engine gets faithfully
  re-routed and crashes the next pod. :class:`PoisonRegistry`
  fingerprints each request; an attempt that dies *abnormally* (engine
  crash, watchdog stall — NOT deadline timeouts, NOT 429/503 sheds)
  marks the fingerprint, and after ``SHAI_POISON_K`` marks the request
  is quarantined: answered 422 with a diagnostic instead of crash-
  looping a third pod. Registries merge through ``/fleet`` so one pod's
  quarantine protects the whole fleet.

Exported counters (cova's ``/fleet`` -> ``"reliability"``;
``scripts/check_metrics_docs.py`` scans the families here):
``shai_hedge_fired_total`` / ``shai_hedge_wins_total`` /
``shai_hedge_cancelled_total`` (hedges launched / hedges that answered
first / losers cancelled), ``shai_retry_budget_spent_total`` /
``shai_retry_budget_exhausted_total`` (tokens drawn / attempts denied —
the runbook split: exhausted rising means the FLEET is browning out,
while ``shai_poison_quarantined_total`` rising means a CLIENT payload is
bad), ``shai_poison_marked_total`` / ``shai_poison_quarantined_total`` /
``shai_poison_rejected_total`` (abnormal deaths marked / fingerprints
crossing K / requests answered 422), and ``shai_route_follow_depth``
(deepest migration-handoff chain cova has followed — bounded by
``SHAI_ROUTE_FOLLOW_MAX``).

Chaos sites (``resilience.faults``): ``hedge.fire`` delays or suppresses
the hedge launch; ``poison.mark`` loses a mark (the quarantine needs one
more abnormal attempt). ``idemp.lookup`` lives with the cache in
``resilience.idempotency``.

Threading: cova is async but the serve layer may share these from lane
threads; every mutation moves under the instance ``_lock`` (declared HOT
in ``analysis/contract.py`` — no I/O, no HTTP, nothing blocking under
any of them; the PR-14 httpx-under-lock lesson).
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, Iterable, List, Optional

from . import faults

#: header cova mints/forwards so pod-side dedup and charge-once work
#: (kept in sync with resilience.idempotency.IDEMP_HEADER)
HEDGE_HEADER = "x-shai-idempotency-key"


def fingerprint(prompt: str, params: Optional[Dict[str, Any]] = None) -> str:
    """Stable request fingerprint for the poison registry: the prompt
    plus the sampling params (sorted, JSON-normalized). Short on purpose
    — it names the request in diagnostics and ``/fleet`` payloads."""
    h = hashlib.sha256()
    h.update(prompt.encode("utf-8", "replace"))
    if params:
        h.update(json.dumps(params, sort_keys=True,
                            default=str).encode("utf-8"))
    return h.hexdigest()[:16]


class RetryBudget:
    """Token bucket fed by primary traffic: ``pct`` tokens per primary
    attempt, one token per hedge/retry. The initial balance equals
    ``burst`` so a cold orchestrator can still retry its very first
    failures, and the bank is capped at the last ``window`` primaries'
    worth of allowance (``pct * window``) — a long healthy stretch can't
    pre-pay an unbounded storm. Total spend is ``<= burst +
    pct * primaries`` by construction (inflow is exactly ``pct`` per
    primary), which is the fleet amplification invariant the chaos sim
    audits."""

    def __init__(self, pct: float = 0.1, burst: float = 2.0,
                 window: int = 600):
        self.pct = max(0.0, float(pct))
        self.burst = max(0.0, float(burst))
        self.window = max(1, int(window))
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._counts = {"spent": 0, "exhausted": 0}

    def note_primary(self, n: int = 1) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.pct * n,
                               max(self.burst, self.pct * self.window))

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            if self._tokens + 1e-9 >= cost:
                self._tokens -= cost
                self._counts["spent"] += 1
                return True
            self._counts["exhausted"] += 1
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"shai_retry_budget_spent_total":
                    float(self._counts["spent"]),
                    "shai_retry_budget_exhausted_total":
                    float(self._counts["exhausted"]),
                    "retry_budget_tokens": round(self._tokens, 3)}


class HedgeGovernor:
    """Adaptive hedge delay: p95 of a bounded window of recent primary
    latencies, clamped to ``[min_s, max_s]``; ``default_s`` until the
    window has enough samples to mean anything."""

    def __init__(self, default_s: float = 0.35, min_s: float = 0.02,
                 max_s: float = 30.0, window: int = 256,
                 min_samples: int = 8):
        self.default_s = float(default_s)
        self.min_s = float(min_s)
        self.max_s = float(max_s)
        self.min_samples = int(min_samples)
        self._lock = threading.Lock()
        self._lat: "deque[float]" = deque(maxlen=int(window))

    def note(self, latency_s: float) -> None:
        if latency_s >= 0:
            with self._lock:
                self._lat.append(float(latency_s))

    def hedge_delay_s(self) -> float:
        with self._lock:
            xs = sorted(self._lat)
        if len(xs) < self.min_samples:
            return max(self.min_s, min(self.max_s, self.default_s))
        # nearest-rank p95 (same definition as bench.py's _pctl)
        idx = max(0, min(len(xs) - 1, int(round(0.95 * len(xs) + 0.5)) - 1))
        return max(self.min_s, min(self.max_s, xs[idx]))


class PoisonRegistry:
    """Bounded fingerprint -> abnormal-death-count table with a K
    threshold. ``merge`` adopts a peer's quarantine set (the ``/fleet``
    gossip), so one pod's crash-loop protects every router."""

    def __init__(self, k: int = 2, max_entries: int = 512):
        self.k = max(1, int(k))
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._counts: "OrderedDict[str, int]" = OrderedDict()
        self._stats = {"marked": 0, "quarantined": 0, "rejected": 0}

    def note_abnormal(self, fp: str) -> int:
        """Record one abnormal death for ``fp``; returns the new count.
        The ``poison.mark`` chaos site can lose the mark (returns the
        OLD count) — proving the K threshold counts marks, not
        attempts."""
        inj = faults.get()
        if inj.should_fail(faults.POISON_MARK):
            with self._lock:
                return self._counts.get(fp, 0)
        with self._lock:
            n = self._counts.get(fp, 0) + 1
            self._counts[fp] = n
            self._counts.move_to_end(fp)
            self._stats["marked"] += 1
            if n == self.k:
                self._stats["quarantined"] += 1
            while len(self._counts) > self.max_entries:
                self._counts.popitem(last=False)
            return n

    def is_quarantined(self, fp: str) -> bool:
        with self._lock:
            return self._counts.get(fp, 0) >= self.k

    def note_rejected(self) -> None:
        with self._lock:
            self._stats["rejected"] += 1

    def quarantined(self) -> List[str]:
        """Fingerprints at/over threshold — the ``/fleet`` gossip set."""
        with self._lock:
            return [fp for fp, n in self._counts.items() if n >= self.k]

    def merge(self, fps: Iterable[str]) -> int:
        """Adopt peer-quarantined fingerprints (idempotent: already-known
        entries only ratchet UP to the threshold)."""
        n_new = 0
        with self._lock:
            for fp in fps:
                fp = str(fp)
                if not fp:
                    continue
                if self._counts.get(fp, 0) < self.k:
                    if fp not in self._counts:
                        n_new += 1
                    self._counts[fp] = self.k
                    self._counts.move_to_end(fp)
            while len(self._counts) > self.max_entries:
                self._counts.popitem(last=False)
        return n_new

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"shai_poison_marked_total": float(self._stats["marked"]),
                    "shai_poison_quarantined_total":
                    float(self._stats["quarantined"]),
                    "shai_poison_rejected_total":
                    float(self._stats["rejected"]),
                    "poison_entries": float(len(self._counts))}


class HedgeStats:
    """The hedge/routing counters cova's dispatch path writes and
    ``/fleet`` scrapes — lock-guarded, the ScalerStats contract."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "fired": 0, "wins": 0, "cancelled": 0,
        }
        self._follow_depth_max = 0

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def note_follow_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self._follow_depth_max:
                self._follow_depth_max = depth

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"shai_hedge_fired_total": float(self._counts["fired"]),
                    "shai_hedge_wins_total": float(self._counts["wins"]),
                    "shai_hedge_cancelled_total":
                    float(self._counts["cancelled"]),
                    "shai_route_follow_depth":
                    float(self._follow_depth_max)}
