"""Per-replica resilience: the pod-level half of the paper's availability
story.

The reference stays up by failing over BETWEEN tiers (capacity-checker
events -> ALB re-weighting, reference ``README.md:157-321``); each pod is
assumed healthy until the LB drops it. This package hardens the pod itself
so a degraded replica degrades *gracefully* instead of hanging:

- :mod:`deadline` — per-request deadlines (``X-SHAI-Deadline-Ms``) carried
  on a contextvar from the HTTP layer down to the engine loop;
- :mod:`admission` — bounded admission in front of ``add_request``: shed
  with 429/503 + ``Retry-After`` instead of parking threads forever;
- :mod:`breaker` — per-backend circuit breakers with jittered exponential
  backoff for the cova fan-out client;
- :mod:`drain` — SIGTERM graceful drain and the engine-step watchdog that
  fails liveness on a stuck dispatch;
- :mod:`faults` — a deterministic, env/endpoint-driven fault injector with
  named sites threaded through the stack (the chaos suite's instrument);
- :mod:`qos` — multi-tenant QoS: priority classes (``X-SHAI-Priority``),
  the weighted-fair scheduler kernel the engine dequeues through, and the
  per-tenant token-rate budget ledger (``X-SHAI-Tenant``,
  ``SHAI_TENANT_BUDGETS``) the admission gate enforces.

Layering: everything here is stdlib-only (plus ``orchestrate.
capacity_checker``'s pure threshold types) so the engine may import it
without pulling in the serve stack.
"""

from .admission import AdmissionGate, Shed  # noqa: F401
from .breaker import CircuitBreaker  # noqa: F401
from .deadline import (  # noqa: F401
    DEADLINE_HEADER,
    Deadline,
    current_deadline,
    deadline_from_headers,
    set_current_deadline,
)
from .drain import DrainController, StepWatchdog  # noqa: F401
from .faults import FaultError, FaultInjector  # noqa: F401
from .qos import (  # noqa: F401
    PRIORITY_HEADER,
    TENANT_HEADER,
    QosTag,
    TenantLedger,
    WeightedFairScheduler,
    current_qos,
    qos_from_headers,
    set_current_qos,
)
