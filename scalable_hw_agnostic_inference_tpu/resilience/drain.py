"""Graceful drain and the engine-step watchdog.

**Drain** (SIGTERM → rolling update / preemption): the pod must finish
what it accepted and refuse what it hasn't. ``DrainController`` is the
shared flag + budget: the serving layer flips ``/readiness`` (and
``/health/ready``) to 503 so the LB stops routing, the admission gate
sheds new work with 503 + ``Retry-After``, in-flight requests run to
completion up to the drain budget, then the engine loop stops and the
process exits. Without this, Kubernetes' default SIGTERM→SIGKILL window
kills mid-decode requests that the client already paid queue time for.

**Watchdog**: a wedged engine dispatch (device hang, deadlocked collective,
runaway compile) leaves the loop thread alive but the engine silent — the
pod keeps answering ``/health`` while every request blackholes. The
watchdog compares the time since the last completed step against N× the
p99 step duration from the obs telemetry ring (floored by ``min_stall_s``)
*while the engine has work*; a trip fails liveness so Kubernetes restarts
the pod instead of serving a black hole. An idle engine never trips — no
work means no steps is the healthy state.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)


class DrainController:
    """One pod's drain state: armed once (idempotent), budgeted, waitable."""

    def __init__(self, budget_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = max(0.0, budget_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._started_at: Optional[float] = None

    @property
    def draining(self) -> bool:
        # shai-lint: allow(guarded-read) deliberately LOCK-FREE: this
        # property runs on the main thread (readiness/admission handlers
        # on the event loop), and the SIGTERM handler — which also runs
        # on the main thread, between bytecodes — takes _lock via
        # begin(); a locked read here could self-deadlock the signal
        # handler against its own thread. A GIL-atomic is-None check of
        # a single reference cannot tear.
        return self._started_at is not None

    def begin(self) -> bool:
        """Arm the drain; True only for the first caller (a duplicate
        SIGTERM must not restart the budget clock)."""
        with self._lock:
            if self._started_at is not None:
                return False
            self._started_at = self._clock()
            return True

    @property
    def remaining_s(self) -> float:
        with self._lock:
            if self._started_at is None:
                return self.budget_s
            return max(0.0, self.budget_s - (self._clock() - self._started_at))

    def wait(self, idle_fn: Callable[[], bool],
             poll_s: float = 0.05, min_remaining: float = 0.0) -> bool:
        """Block until ``idle_fn()`` or the budget runs out; True = drained
        clean, False = budget exhausted with work still in flight.

        ``min_remaining``: stop waiting while that much budget is still
        left — the migrate phase's reservation (live migration ships the
        long tail with budget to spare, instead of discovering at the
        deadline that nothing can ship anymore)."""
        while True:
            if idle_fn():
                return True
            if self.remaining_s <= max(0.0, min_remaining):
                return False
            time.sleep(poll_s)


class StepWatchdog:
    """Detect a stuck engine dispatch from the obs step telemetry.

    ``telemetry_provider`` returns the engine's
    ``obs.steploop.StepTelemetry`` (or None before load); ``busy_fn``
    reports whether the engine has work. Threshold: ``max(min_stall_s,
    multiplier * p99 step duration)`` — p99 from the telemetry's recent
    step ring, so a tier whose steps legitimately take seconds (large
    batch, long context) gets a proportionally longer leash than a tier
    stepping at 10 ms.
    """

    def __init__(self, telemetry_provider: Callable[[], Any],
                 busy_fn: Callable[[], bool], multiplier: float = 30.0,
                 min_stall_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.telemetry_provider = telemetry_provider
        self.busy_fn = busy_fn
        self.multiplier = multiplier
        self.min_stall_s = min_stall_s
        self._clock = clock
        # when we first OBSERVED the engine busy after an idle stretch:
        # the loop only steps while it has work, so time-since-last-step
        # includes the idle gap — measuring from the idle->busy transition
        # keeps a pod that idled an hour from reading as stalled the
        # moment its next request arrives
        self._busy_since: Optional[float] = None

    def threshold_s(self, tele) -> float:
        p99 = tele.step_duration_p99()
        return max(self.min_stall_s, self.multiplier * p99)

    def check(self) -> Optional[str]:
        """Non-None = the liveness failure reason (the pod should restart)."""
        try:
            tele = self.telemetry_provider()
        except Exception:
            return None
        if tele is None:
            return None
        try:
            busy = self.busy_fn()
        except Exception:
            return None
        now = self._clock()
        if not busy:
            self._busy_since = None
            return None  # idle: no steps is the healthy state
        if self._busy_since is None:
            self._busy_since = now
        age = min(tele.last_step_age_s(now=now), now - self._busy_since)
        limit = self.threshold_s(tele)
        if age > limit:
            return (f"engine step stalled: {age:.1f}s since last completed "
                    f"step with work pending (limit {limit:.1f}s = "
                    f"max({self.min_stall_s:.1f}s, {self.multiplier:.0f}x "
                    f"p99 step))")
        return None
