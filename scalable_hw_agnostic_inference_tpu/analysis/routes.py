"""Trace-exclusion checker: debug/poll GET routes stay off the flight ring.

PR 7's review caught ``GET /profile`` missing from ``trace_exclude``: a
dashboard polling profiler state at 2 Hz would have evicted every real
request timeline from the bounded flight-recorder ring — the postmortem
buffer erased by the tool meant to read it. This rule makes that class
mechanical: every GET route registered in ``serve/app.py`` that is a
debug surface (``/debug/...``) or a declared poll route
(``contract.poll_routes``) must be a member of the static
``trace_exclude`` set (the asgi default literal plus ``app.trace_exclude
|= {...}`` updates).
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Module, dotted, snippet_of

RULE = "trace-exclude"


def _string_set(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def _excluded_routes(modules: List[Module], contract) -> Set[str]:
    excluded: Set[str] = set()
    for module in modules:
        if module.relpath not in contract.trace_files:
            continue
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (dotted(t) or "").endswith("trace_exclude"):
                        excluded |= _string_set(node.value)
            elif isinstance(node, ast.AugAssign) and \
                    (dotted(node.target) or "").endswith("trace_exclude"):
                excluded |= _string_set(node.value)
    return excluded


def _get_routes(module: Module):
    """(pattern, decorator node) for every ``@app.get("...")`` route."""
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            if isinstance(deco, ast.Call) \
                    and isinstance(deco.func, ast.Attribute) \
                    and deco.func.attr == "get" and deco.args \
                    and isinstance(deco.args[0], ast.Constant) \
                    and isinstance(deco.args[0].value, str):
                yield deco.args[0].value, deco


def check(modules: List[Module], contract) -> List[Finding]:
    excluded = _excluded_routes(modules, contract)
    findings: List[Finding] = []
    for module in modules:
        if module.relpath not in contract.trace_files:
            continue
        for pattern, deco in _get_routes(module):
            if not (pattern.startswith("/debug/")
                    or pattern in contract.poll_routes):
                continue
            if pattern in excluded:
                continue
            allowed, reason, problem = module.allow_at(deco, RULE)
            msg = ("debug/poll GET route is missing from trace_exclude — "
                   "polling it would evict real request timelines from "
                   "the flight ring")
            if problem:
                msg += f" ({problem})"
            findings.append(Finding(
                rule=RULE, path=module.relpath, line=deco.lineno,
                context=pattern, message=msg, allowed=allowed,
                reason=reason, snippet=snippet_of(module, deco)))
    return findings
