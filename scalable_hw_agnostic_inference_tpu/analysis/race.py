"""shai-race: lock-order, blocking-under-lock, and guarded-read checks.

The third analysis leg beside the AST rules (``run_all``) and the IR pass
(``analysis/ir``). The stack runs a half-dozen long-lived threads
(engine loop, kvtier copy-out worker, httpd, drain worker, watchdog,
capacity checker) coordinating through ~25 locks; the ``thread`` rule
checks that declared state is *written* under its lock, but nothing
detected lock-order inversions, blocking calls held under hot locks, or
unguarded *reads* of multi-field snapshots. This module turns those three
bug classes into findings on the same Finding/baseline/allow machinery.

Rules (``contract.race`` + the ``thread_contract`` ClassPolicy tables are
the ground truth; a lock's IDENTITY is ``"<Class>.<attr>"`` for locks a
contract class owns, or a declared module-lock id like
``app.inflight_lock``):

- ``lock-order`` — builds a lock-acquisition graph from lexical ``with
  <lock>`` nestings plus two levels of intra-package call-graph
  propagation (method calls made while a lock is held, the callee
  resolved through the ClassPolicy ``instance_markers``). Every observed
  cross-lock edge must be derivable from the declared partial order
  (``contract.race.lock_order``); an edge whose reverse is derivable, a
  re-acquisition of a held lock, or any cycle in the observed graph is a
  potential deadlock. The committed contract declares an EMPTY order —
  "no lock nesting at all" — so any nesting is a finding until a pair is
  deliberately added.
- ``blocking-under-lock`` — unbounded blocking calls (``queue.get/put``
  with no timeout, ``Future.result()``, ``Thread.join()``,
  ``Event.wait()``, ``time.sleep``, socket/httpx/requests calls,
  ``.block_until_ready()`` / ``jax.device_get`` / ``np.asarray`` device
  fetches) lexically inside a ``with <lock>`` body on a declared HOT
  lock (``contract.race.hot_locks``): every thread in the process
  eventually serializes behind those locks, so one blocked holder stalls
  the request path fleet-wide.
- ``guarded-read`` — attributes a ClassPolicy declares ``lock_guarded``
  must also be *read* under that lock (the write-only ``thread`` rule
  misses torn reads of multi-field snapshots like the ``/stats``
  collectors). Covers in-class ``self.<attr>`` loads, loads reached
  through ``instance_markers`` from non-owning modules, and the
  ``dict_guards`` closure dicts (``serve.app``'s ``state``).

Deliberate exceptions carry the standard grammar, e.g.::

    # shai-lint: allow(guarded-read) caller-holds-lock helper

CLI: ``python scripts/shai_lint.py --race`` (same 0/1/2 exit contract and
rule-aware baseline staleness as ``--ir``); ``scripts/check_all.py`` runs
it in the one-exit-code gate. The dynamic twin of these static tables is
the deterministic interleaving harness in ``tests/schedutil.py`` /
``tests/test_race.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, dotted, resolved_dotted, snippet_of
from .threads import _MUTATORS, _matches_marker

RULE_ORDER = "lock-order"
RULE_BLOCK = "blocking-under-lock"
RULE_READ = "guarded-read"
RACE_RULES = (RULE_ORDER, RULE_BLOCK, RULE_READ)

#: dotted call targets that block unconditionally
_BLOCKING_FUNCS = {
    "time.sleep": "time.sleep()",
    "jax.device_get": "device fetch jax.device_get(...)",
    "numpy.asarray": "device fetch numpy.asarray(...)",
    "numpy.array": "device fetch numpy.array(...)",
    "numpy.ascontiguousarray": "device copy numpy.ascontiguousarray(...)",
}
#: dotted prefixes whose calls are network I/O
_BLOCKING_PREFIXES = ("socket.", "requests.", "httpx.", "urllib.")


#: a lexical lock scope ends at a function boundary: code inside a nested
#: def/lambda runs LATER, when the enclosing ``with`` has long released
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _policy_locks(policy) -> Set[str]:
    """The lock attribute names a ClassPolicy owns."""
    return set(policy.locks) | set(policy.lock_guarded.values())


def _scope_walk(root: ast.AST):
    """Walk ``root``'s body without descending into nested function
    definitions or lambdas (their bodies execute in a different dynamic
    scope)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_shai_parent", None)
    return None


def _enclosing_callable(node: ast.AST) -> str:
    """``Class.method`` / function-name context for a finding."""
    parts: List[str] = []
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_shai_parent", None)
    return ".".join(reversed(parts)) or "<module>"


def _resolve_lock(module: Module, expr: ast.AST, contract) -> Optional[str]:
    """The declared lock identity a ``with`` target names, or None for
    locks outside the contract (ignored by every rule)."""
    d = dotted(expr)
    if d is None:
        return None
    mod_locks = contract.race.module_locks.get(module.relpath, {})
    if d in mod_locks:
        return mod_locks[d]
    if d.startswith("self."):
        attr = d[len("self."):]
        if "." not in attr:
            cls = _enclosing_class(expr)
            if cls is not None:
                policy = contract.thread_contract.get(cls.name)
                if policy is not None and attr in _policy_locks(policy):
                    return f"{cls.name}.{attr}"
            return None
        # `self.<other>.<lock>` reaches ANOTHER object's lock: resolve
        # through the instance markers below
    attr = d.rsplit(".", 1)[-1]
    for cls_name, policy in contract.thread_contract.items():
        if attr in _policy_locks(policy) and policy.instance_markers \
                and _matches_marker(d, policy.instance_markers):
            return f"{cls_name}.{attr}"
    return None


def _held_locks(node: ast.AST, module: Module, contract) -> List[str]:
    """Declared locks held lexically at ``node`` (innermost last). A node
    inside a ``with`` statement's own items (the acquisition expression)
    does not yet hold that statement's locks, and the walk STOPS at the
    first enclosing function boundary — a deferred callback defined
    under a ``with`` runs after the release."""
    held: List[str] = []
    child: ast.AST = node
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, _FUNC_NODES) and cur is not node:
            break
        if isinstance(cur, (ast.With, ast.AsyncWith)) \
                and not isinstance(child, ast.withitem):
            for item in cur.items:
                lock = _resolve_lock(module, item.context_expr, contract)
                if lock is not None:
                    held.append(lock)
        child = cur
        cur = getattr(cur, "_shai_parent", None)
    return list(reversed(held))


def _finding(module: Module, node: ast.AST, rule: str, context: str,
             message: str) -> Finding:
    allowed, reason, problem = module.allow_at(node, rule)
    if problem:
        message += f" ({problem})"
    return Finding(rule=rule, path=module.relpath, line=node.lineno,
                   context=context, message=message, allowed=allowed,
                   reason=reason, snippet=snippet_of(module, node))


# -- lock-order ---------------------------------------------------------------

def _method_direct_locks(modules: Sequence[Module], contract
                         ) -> Dict[Tuple[str, str], Set[str]]:
    """(class, method) -> lock identities the method body acquires
    directly (``with`` targets resolved through the contract)."""
    out: Dict[Tuple[str, str], Set[str]] = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) \
                    or node.name not in contract.thread_contract:
                continue
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                acquired: Set[str] = set()
                # same-scope walk: a `with` inside a nested def is NOT
                # acquired by calling this method
                for n in _scope_walk(meth):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            lock = _resolve_lock(module, item.context_expr,
                                                 contract)
                            if lock is not None:
                                acquired.add(lock)
                out[(node.name, meth.name)] = acquired
    return out


def _callees(module: Module, call: ast.Call, contract,
             methods: Dict[Tuple[str, str], Set[str]]
             ) -> List[Tuple[str, str]]:
    """Contract-class methods a call site may dispatch to: ``self.m()``
    resolves within the enclosing class; ``<marker-path>.m()`` resolves
    through every ClassPolicy whose instance markers match the receiver."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return []
    name = f.attr
    recv = dotted(f.value)
    out: List[Tuple[str, str]] = []
    if recv == "self":
        cls = _enclosing_class(call)
        if cls is not None and (cls.name, name) in methods:
            out.append((cls.name, name))
        return out
    full = dotted(f)
    if full is None:
        return out
    for cls_name, policy in contract.thread_contract.items():
        if (cls_name, name) in methods and policy.instance_markers \
                and _matches_marker(full, policy.instance_markers):
            out.append((cls_name, name))
    return out


def _transitive_closure(pairs: Sequence[Tuple[str, str]]
                        ) -> Set[Tuple[str, str]]:
    closure = set(pairs)
    changed = True
    while changed:
        changed = False
        for a, b in list(closure):
            for c, d in list(closure):
                if b == c and (a, d) not in closure:
                    closure.add((a, d))
                    changed = True
    return closure


def _cycle_nodes(edges: Set[Tuple[str, str]]) -> Set[str]:
    """Lock identities on at least one directed cycle of ``edges``."""
    reach = _transitive_closure(tuple(edges))
    return {a for a, b in reach if (b, a) in reach or a == b}


def check_lock_order(modules: Sequence[Module], contract) -> List[Finding]:
    findings: List[Finding] = []
    declared = _transitive_closure(contract.race.lock_order)
    if any(a == b for a, b in declared):
        findings.append(Finding(
            rule=RULE_ORDER, path="analysis/contract.py", line=1,
            context="<contract>",
            message="declared lock_order is cyclic — the partial order "
                    "must be a DAG", snippet="lock_order"))
    methods = _method_direct_locks(modules, contract)
    # depth 2: a method also "acquires" what the contract methods it
    # calls acquire directly
    deep: Dict[Tuple[str, str], Set[str]] = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) \
                    or node.name not in contract.thread_contract:
                continue
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                extra: Set[str] = set()
                for n in _scope_walk(meth):
                    if isinstance(n, ast.Call):
                        for callee in _callees(module, n, contract,
                                               methods):
                            extra |= methods.get(callee, set())
                deep[(node.name, meth.name)] = \
                    methods.get((node.name, meth.name), set()) | extra
    # observed edges, with one representative site each
    edge_sites: Dict[Tuple[str, str], Tuple[Module, ast.AST, str]] = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held = _held_locks(node, module, contract)
                acquired = [lock for item in node.items
                            for lock in
                            [_resolve_lock(module, item.context_expr,
                                           contract)]
                            if lock is not None]
                # multi-item `with a, b:` orders left-to-right
                for i, a in enumerate(acquired):
                    for h in held + acquired[:i]:
                        edge_sites.setdefault(
                            (h, a),
                            (module, node,
                             f"acquires `{a}` while holding `{h}`"))
            elif isinstance(node, ast.Call):
                held = _held_locks(node, module, contract)
                if not held:
                    continue
                for cls_name, meth_name in _callees(module, node, contract,
                                                    methods):
                    for lock in sorted(deep.get((cls_name, meth_name),
                                                set())):
                        for h in held:
                            edge_sites.setdefault(
                                (h, lock),
                                (module, node,
                                 f"calls {cls_name}.{meth_name}() which "
                                 f"acquires `{lock}` while holding "
                                 f"`{h}`"))
    cyclic = _cycle_nodes(set(edge_sites))
    for (src, dst), (module, node, why) in sorted(
            edge_sites.items(), key=lambda kv: (kv[1][0].relpath,
                                                kv[1][1].lineno)):
        if src == dst:
            findings.append(_finding(
                module, node, RULE_ORDER, _enclosing_callable(node),
                f"{why} — re-acquiring a held non-reentrant lock "
                f"self-deadlocks"))
        elif (src, dst) not in declared:
            if (dst, src) in declared:
                detail = (f"contradicts the declared order "
                          f"`{dst}` < `{src}` — potential deadlock")
            elif src in cyclic and dst in cyclic:
                detail = ("closes an acquisition cycle — potential "
                          "deadlock")
            else:
                detail = ("undeclared nesting: add the pair to "
                          "contract.race.lock_order or restructure to "
                          "release first")
            findings.append(_finding(
                module, node, RULE_ORDER, _enclosing_callable(node),
                f"{why} — {detail}"))
    return findings


# -- blocking-under-lock ------------------------------------------------------

def _bounded_call(call: ast.Call) -> bool:
    """True when a timeout/block/blocking keyword actually BOUNDS the
    call: ``timeout=`` anything but a literal None, or ``block=False`` /
    ``blocking=False``. An explicit ``timeout=None`` or ``block=True``
    spells the unbounded default out loud — still a finding."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            if not (isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                return True
        elif kw.arg in ("block", "blocking"):
            if isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
    return False


def _blocking_kind(module: Module, call: ast.Call) -> Optional[str]:
    """Why this call blocks unboundedly, or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        a = f.attr
        if a == "block_until_ready":
            return ".block_until_ready()"
        if _bounded_call(call):
            return None
        if a == "result" and not call.args:
            return ".result() with no timeout"
        if a == "join" and not call.args:
            return ".join() with no timeout"
        if a == "wait" and not call.args:
            return ".wait() with no timeout"
        if a == "get" and not call.args:
            return "blocking .get() with no timeout"
        if a == "put" and len(call.args) == 1:
            return "blocking .put() with no timeout"
        if a == "acquire" and not call.args:
            return "blocking .acquire() with no timeout"
    d = resolved_dotted(module, f)
    if d in _BLOCKING_FUNCS:
        return _BLOCKING_FUNCS[d]
    if d is not None and d.startswith(_BLOCKING_PREFIXES):
        return f"network call {d}(...)"
    return None


def check_blocking(modules: Sequence[Module], contract) -> List[Finding]:
    findings: List[Finding] = []
    hot = set(contract.race.hot_locks)
    if not hot:
        return findings
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _blocking_kind(module, node)
            if kind is None:
                continue
            held_hot = [h for h in _held_locks(node, module, contract)
                        if h in hot]
            if not held_hot:
                continue
            findings.append(_finding(
                module, node, RULE_BLOCK, _enclosing_callable(node),
                f"{kind} under hot lock `{held_hot[-1]}` — every thread "
                f"serializing on that lock stalls behind this call"))
    return findings


# -- guarded-read -------------------------------------------------------------

def _holds_lock_scoped(node: ast.AST, lock_names: Set[str]) -> bool:
    """Like ``threads._holds_lock`` but stops at function boundaries —
    a deferred callback defined under ``with <lock>`` runs unlocked."""
    child: ast.AST = node
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, _FUNC_NODES):
            return False
        if isinstance(cur, (ast.With, ast.AsyncWith)) \
                and not isinstance(child, ast.withitem):
            for item in cur.items:
                if dotted(item.context_expr) in lock_names:
                    return True
        child = cur
        cur = getattr(cur, "_shai_parent", None)
    return False


def _is_mutator_receiver(attr_node: ast.AST) -> bool:
    """True when the load is the receiver of a mutator call
    (``self._x.append(...)``) — that's a WRITE site, owned by the
    ``thread`` rule."""
    parent = getattr(attr_node, "_shai_parent", None)
    if not isinstance(parent, ast.Attribute) or parent.value is not attr_node:
        return False
    gp = getattr(parent, "_shai_parent", None)
    return isinstance(gp, ast.Call) and gp.func is parent \
        and parent.attr in _MUTATORS


def _is_store_base(attr_node: ast.AST) -> bool:
    """True when the load is the base of a subscript STORE/DELETE
    (``self._x[k] = v`` / ``del self._x[k]``) — write sites."""
    parent = getattr(attr_node, "_shai_parent", None)
    return isinstance(parent, ast.Subscript) \
        and parent.value is attr_node \
        and isinstance(parent.ctx, (ast.Store, ast.Del))


def _holds(node: ast.AST, module: Module, contract, lock_id: str) -> bool:
    return lock_id in _held_locks(node, module, contract)


def check_guarded_reads(modules: Sequence[Module], contract
                        ) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        # 1) in-class reads of declared lock-guarded attrs
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef) \
                    or cls.name not in contract.thread_contract:
                continue
            policy = contract.thread_contract[cls.name]
            if not policy.lock_guarded:
                continue
            seen: Set[Tuple[int, str]] = set()
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if meth.name in policy.init_methods:
                    continue
                for n in ast.walk(meth):
                    if not (isinstance(n, ast.Attribute)
                            and isinstance(n.ctx, ast.Load)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == "self"
                            and n.attr in policy.lock_guarded):
                        continue
                    if _is_mutator_receiver(n) or _is_store_base(n):
                        continue
                    lock = policy.lock_guarded[n.attr]
                    if _holds(n, module, contract,
                              f"{cls.name}.{lock}"):
                        continue
                    stmt = n
                    while not isinstance(stmt, ast.stmt) \
                            and getattr(stmt, "_shai_parent", None) \
                            is not None:
                        stmt = stmt._shai_parent  # type: ignore
                    if (stmt.lineno, n.attr) in seen:
                        continue  # two loads in one statement: one finding
                    seen.add((stmt.lineno, n.attr))
                    findings.append(_finding(
                        module, stmt, RULE_READ,
                        f"{cls.name}.{meth.name}",
                        f"read of lock-guarded attr `{n.attr}` outside "
                        f"`with self.{lock}` — a concurrent writer can "
                        f"tear this snapshot"))
        # 2) marker-resolved reads from non-owning modules
        for cls_name, policy in contract.thread_contract.items():
            if not policy.lock_guarded or not policy.instance_markers:
                continue
            if module.relpath in policy.owning_modules:
                continue
            for n in ast.walk(module.tree):
                if not (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, ast.Load)
                        and n.attr in policy.lock_guarded):
                    continue
                d = dotted(n)
                if d is None or d.startswith("self.") \
                        or not _matches_marker(d, policy.instance_markers):
                    continue
                if _is_mutator_receiver(n) or _is_store_base(n):
                    continue
                findings.append(_finding(
                    module, n, RULE_READ, _enclosing_callable(n),
                    f"read of `{d}` — {cls_name}.{n.attr} is "
                    f"lock-guarded; read it through a snapshot method, "
                    f"not directly across threads"))
        # 3) guarded closure dicts (the dict_guards write rule's read twin)
        guards = contract.dict_guards.get(module.relpath, {})
        for n in ast.walk(module.tree):
            if not (isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Load)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in guards):
                continue
            keys, lock = guards[n.value.id]
            key = n.slice
            if not (isinstance(key, ast.Constant) and key.value in keys):
                continue
            mod_locks = contract.race.module_locks.get(module.relpath, {})
            lock_ids = {lock, mod_locks.get(lock, lock)}
            if set(_held_locks(n, module, contract)) & lock_ids:
                continue
            # fall back to a lexical check on the raw lock name (the
            # closure lock may not be a declared race lock) — function-
            # boundary-aware like _held_locks
            if _holds_lock_scoped(n, {lock}):
                continue
            findings.append(_finding(
                module, n, RULE_READ, _enclosing_callable(n),
                f"read of `{n.value.id}[\"{key.value}\"]` outside "
                f"`with {lock}` — a concurrent writer can tear this "
                f"snapshot"))
    return findings


# -- runner -------------------------------------------------------------------

def run_race(modules: Optional[List[Module]] = None,
             contract=None) -> List[Finding]:
    """Run the three race rules; returns ALL findings (allowed included,
    flagged), sorted like :func:`core.run_all`."""
    from .contract import DEFAULT_CONTRACT
    from .core import iter_modules

    contract = contract or DEFAULT_CONTRACT
    if modules is None:
        modules = iter_modules()
    findings: List[Finding] = []
    findings += check_lock_order(modules, contract)
    findings += check_blocking(modules, contract)
    findings += check_guarded_reads(modules, contract)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
