"""The jaxpr-lint program registry: build every registered executable
factory at tiny geometry for IR inspection.

Each builder returns an :class:`~.program.IrProgram` wrapping the REAL
factory from ``engine/runner.py`` / ``core/aot.py`` / ``parallel/ring.py``
— never a copy of its body — called with a config small enough that
trace+lower stays in the hundreds of milliseconds. ``@tp2``/``@sp2``
variants build on a 2-way mesh of virtual CPU devices (the same
virtual-device discipline as the dryrun legs and ``tests/conftest.py``);
``@tp2_paged`` lowers the Pallas paged path for the ``tpu`` platform
(trace + SPMD partition only — the Mosaic kernel cannot compile on CPU,
which is also why donation aliasing for that leg is judged at the
lowering tier).

Geometry is shared across builders so composition members compare
like-for-like: B=2 slots, block_size=8, blocks_per_seq=4, 16-block pool,
one 16-token prefill bucket, k=2 speculative draft.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .program import IrProgram

# shared tiny geometry (every builder; compositions must match shapes)
B = 2            # slot batch
BS = 8           # block size
BPS = 4          # blocks per sequence
TOT = 16         # pool blocks
BUCKET = 16      # prefill bucket
K_SPEC = 2       # speculative draft length
LV = 8           # vision-state rows (cross programs)

RUNNER = "engine/runner.py"
AOT = "core/aot.py"
RING = "parallel/ring.py"


def _tiny_cfg(cross: bool = False):
    from ...models.llama import LlamaConfig

    if cross:
        return LlamaConfig(
            vocab_size=128, dim=32, n_layers=3, n_heads=2, n_kv_heads=2,
            mlp_dim=64, max_seq_len=64, tie_embeddings=True,
            cross_attention_layers=(1,))
    return LlamaConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        mlp_dim=64, max_seq_len=64, tie_embeddings=True)


def _mesh(axis: str):
    """A 2-way mesh over virtual CPU devices (dryrun discipline)."""
    import jax

    from ...core.mesh import build_mesh

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError(
            f"jaxpr-lint needs >= 2 devices for @{axis}2 programs; jax "
            f"sees {len(devs)}. Run via scripts/shai_lint.py --ir (it "
            f"forces the 8-virtual-CPU-device platform) or set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 before jax import.")
    return build_mesh(f"{axis}=2", devices=devs[:2])


def _param_sds(cfg, shardings=None):
    import jax

    from ...models.llama import geometry_params

    shapes = jax.eval_shape(lambda: geometry_params(cfg))
    if shardings is None:
        return shapes, jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), shapes)
    return shapes, jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        shapes, shardings.params)


def _kv_sds(cfg, shardings=None, quant: bool = False):
    import jax
    import jax.numpy as jnp

    n_self = cfg.n_layers - len(cfg.cross_attention_layers)
    shape = (TOT, BS, cfg.n_kv_heads, cfg.head_dim)
    sc_shape = (TOT, cfg.n_kv_heads)
    blk_dt = jnp.int8 if quant else jnp.bfloat16

    def lay():
        if shardings is None:
            d = {n: jax.ShapeDtypeStruct(shape, blk_dt) for n in ("k", "v")}
            if quant:
                d.update({n: jax.ShapeDtypeStruct(sc_shape, jnp.float32)
                          for n in ("ks", "vs")})
            return d
        d = {n: jax.ShapeDtypeStruct(shape, blk_dt,
                                     sharding=shardings.kv_layer[n])
             for n in ("k", "v")}
        if quant:
            d.update({n: jax.ShapeDtypeStruct(
                sc_shape, jnp.float32, sharding=shardings.kv_scale)
                for n in ("ks", "vs")})
        return d

    return [lay() for _ in range(n_self)]


def _sds(shape, dtype, sharding=None):
    import jax

    if sharding is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def _engine_shardings(cfg, mesh):
    import jax

    from ...engine.runner import EngineShardings
    from ...models.llama import geometry_params

    shapes = jax.eval_shape(lambda: geometry_params(cfg))
    return EngineShardings(mesh, shapes, cfg)


def _decode_args(cfg, rep=None, shardings=None, quant: bool = False):
    import jax.numpy as jnp

    _, params = _param_sds(cfg, shardings)
    kv = _kv_sds(cfg, shardings, quant=quant)
    return (params, kv,
            _sds((B,), jnp.int32, rep),        # tokens
            _sds((B,), jnp.int32, rep),        # pos
            _sds((B, BPS), jnp.int32, rep),    # tables
            _sds((B,), jnp.bool_, rep),        # active
            _sds((2,), jnp.uint32, rep),       # rng
            _sds((B,), jnp.float32, rep),      # temperature
            _sds((B,), jnp.int32, rep),        # top_k
            _sds((B,), jnp.float32, rep))      # top_p


def _build_decode(key: str, feedback: bool, tp: bool = False,
                  paged: bool = False, artifact: bool = False,
                  compile_cpu: bool = False, ragged: bool = False,
                  kv_quant: bool = False) -> IrProgram:
    from ...engine.runner import make_decode

    cfg = _tiny_cfg()
    sh = _engine_shardings(cfg, _mesh("tp")) if tp else None
    fn = make_decode(cfg, BS, BPS, max_num_seqs=B, shardings=sh,
                     paged=paged, feedback=feedback, ragged=ragged,
                     kv_quant=kv_quant)
    args = _decode_args(cfg, rep=sh.rep if sh else None, shardings=sh,
                        quant=kv_quant)
    return IrProgram(
        key=key, factory="make_decode", anchor_path=RUNNER, jitted=fn,
        args=args, donate_args=(1, 3) if feedback else (1,),
        compile_cpu=compile_cpu,
        lowering_platforms=("tpu",) if paged else None,
        artifact=artifact)


def _build_prefill(key: str, tp: bool = False,
                   kv_quant: bool = False) -> IrProgram:
    import jax.numpy as jnp

    from ...engine.runner import make_prefill

    cfg = _tiny_cfg()
    sh = _engine_shardings(cfg, _mesh("tp")) if tp else None
    fn = make_prefill(cfg, BS, BPS, BUCKET, n_seqs=1, shardings=sh,
                      kv_quant=kv_quant)
    rep = sh.rep if sh else None
    _, params = _param_sds(cfg, sh)
    args = (params, _kv_sds(cfg, sh, quant=kv_quant),
            _sds((1, BUCKET), jnp.int32, rep),
            _sds((1,), jnp.int32, rep),
            _sds((1, BPS), jnp.int32, rep))
    return IrProgram(key=key, factory="make_prefill", anchor_path=RUNNER,
                     jitted=fn, args=args, donate_args=(1,),
                     compile_cpu=not tp)


def _build_prefill_cont(key: str) -> IrProgram:
    import jax.numpy as jnp

    from ...engine.runner import make_prefill_cont

    cfg = _tiny_cfg()
    fn = make_prefill_cont(cfg, BS, BPS, BUCKET, start_blocks=2)
    _, params = _param_sds(cfg)
    args = (params, _kv_sds(cfg),
            _sds((1, BUCKET), jnp.int32),
            _sds((1,), jnp.int32),
            _sds((1, BPS), jnp.int32))
    return IrProgram(key=key, factory="make_prefill_cont",
                     anchor_path=RUNNER, jitted=fn, args=args,
                     donate_args=(1,))


def _build_rcont(key: str, tp: bool = False,
                 kv_quant: bool = False) -> IrProgram:
    # the ragged continuation (SHAI_RAGGED_ATTENTION): chunk start as DATA
    # — ONE executable per chunk bucket. Built on the CPU platform, so the
    # traced attention is the XLA gather reference (the Pallas leg is
    # covered by decode_ragged@tp2's tpu lowering).
    import jax.numpy as jnp

    from ...engine.runner import make_prefill_cont

    cfg = _tiny_cfg()
    sh = _engine_shardings(cfg, _mesh("tp")) if tp else None
    fn = make_prefill_cont(cfg, BS, BPS, BUCKET, shardings=sh,
                           kv_quant=kv_quant, ragged=True)
    rep = sh.rep if sh else None
    _, params = _param_sds(cfg, sh)
    args = (params, _kv_sds(cfg, sh, quant=kv_quant),
            _sds((1, BUCKET), jnp.int32, rep),
            _sds((1,), jnp.int32, rep),
            _sds((1, BPS), jnp.int32, rep),
            _sds((1,), jnp.int32, rep))
    return IrProgram(key=key, factory="make_prefill_cont",
                     anchor_path=RUNNER, jitted=fn, args=args,
                     donate_args=(1,), compile_cpu=not tp)


def _build_fused(key: str, feedback: bool, tp: bool = False,
                 paged: bool = False, kv_quant: bool = False,
                 compile_cpu: bool = False) -> IrProgram:
    # the fused mixed-phase step (SHAI_FUSED_STEP): decode rows + one
    # continuation-chunk window in ONE dispatch. CPU legs trace the
    # per-section reference attentions; the @tp2 leg lowers the flattened
    # mixed-phase Pallas call for the tpu platform (paged=True forces the
    # kernel, dryrun-style, like decode@tp2_paged)
    import jax.numpy as jnp

    from ...engine.runner import make_fused_step

    cfg = _tiny_cfg()
    sh = _engine_shardings(cfg, _mesh("tp")) if tp else None
    fn = make_fused_step(cfg, BS, BPS, B, BUCKET, shardings=sh,
                         paged=paged, feedback=feedback, kv_quant=kv_quant)
    rep = sh.rep if sh else None
    args = _decode_args(cfg, rep=rep, shardings=sh, quant=kv_quant) + (
        _sds((1, BUCKET), jnp.int32, rep),     # c_ids
        _sds((1,), jnp.int32, rep),            # c_ntext
        _sds((1, BPS), jnp.int32, rep),        # c_table
        _sds((1,), jnp.int32, rep))            # c_start
    return IrProgram(
        key=key, factory="make_fused_step", anchor_path=RUNNER, jitted=fn,
        args=args, donate_args=(1, 3) if feedback else (1,),
        compile_cpu=compile_cpu,
        lowering_platforms=("tpu",) if paged else None)


def _build_tier_restore_quant(key: str) -> IrProgram:
    # the quantized restore scatter: int8 blocks + f32 scale rows move in
    # ONE donated call per layer (all four pool buffers donate-and-rebind)
    import jax.numpy as jnp

    from ...kvtier.restore import make_tier_restore

    cfg = _tiny_cfg()
    fn = make_tier_restore(quant=True)
    pool = (TOT, BS, cfg.n_kv_heads, cfg.head_dim)
    sc = (TOT, cfg.n_kv_heads)
    host = (2, BS, cfg.n_kv_heads, cfg.head_dim)
    host_sc = (2, cfg.n_kv_heads)
    args = (_sds(pool, jnp.int8), _sds(pool, jnp.int8),
            _sds(sc, jnp.float32), _sds(sc, jnp.float32),
            _sds((2,), jnp.int32),
            _sds(host, jnp.int8), _sds(host, jnp.int8),
            _sds(host_sc, jnp.float32), _sds(host_sc, jnp.float32))
    return IrProgram(key=key, factory="make_tier_restore",
                     anchor_path="kvtier/restore.py", jitted=fn, args=args,
                     donate_args=(0, 1, 2, 3), compile_cpu=True)


def _build_verify(key: str) -> IrProgram:
    import jax.numpy as jnp

    from ...engine.runner import make_verify

    cfg = _tiny_cfg()
    fn = make_verify(cfg, BS, BPS, max_num_seqs=B, k=K_SPEC, paged=False)
    _, params = _param_sds(cfg)
    args = (params, _kv_sds(cfg),
            _sds((B, K_SPEC + 1), jnp.int32),
            _sds((B,), jnp.int32),
            _sds((B, BPS), jnp.int32),
            _sds((B,), jnp.bool_),
            _sds((2,), jnp.uint32),
            _sds((B,), jnp.float32),
            _sds((B,), jnp.int32),
            _sds((B,), jnp.float32))
    return IrProgram(key=key, factory="make_verify", anchor_path=RUNNER,
                     jitted=fn, args=args, donate_args=(1,))


def _build_cross_kv(key: str) -> IrProgram:
    import jax.numpy as jnp

    from ...engine.runner import make_cross_kv

    cfg = _tiny_cfg(cross=True)
    fn = make_cross_kv(cfg)
    _, params = _param_sds(cfg)
    args = (params, _sds((LV, cfg.dim), jnp.float32))
    return IrProgram(key=key, factory="make_cross_kv", anchor_path=RUNNER,
                     jitted=fn, args=args, donate_args=())


def _build_cross_slot_write(key: str) -> IrProgram:
    import jax.numpy as jnp

    from ...engine.runner import make_cross_slot_write

    cfg = _tiny_cfg(cross=True)
    fn = make_cross_slot_write(cfg)
    n_cross = len(cfg.cross_attention_layers)
    cross_kv = [{n: _sds((B, LV, cfg.n_kv_heads, cfg.head_dim),
                         jnp.bfloat16) for n in ("k", "v")}
                for _ in range(n_cross)]
    per_layer = [{n: _sds((LV, cfg.n_kv_heads, cfg.head_dim),
                          jnp.bfloat16) for n in ("k", "v")}
                 for _ in range(n_cross)]
    args = (cross_kv, per_layer, _sds((), jnp.int32))
    return IrProgram(key=key, factory="make_cross_slot_write",
                     anchor_path=RUNNER, jitted=fn, args=args,
                     donate_args=(0,), compile_cpu=True)


def _build_ring(key: str, causal: bool) -> IrProgram:
    import jax
    import jax.numpy as jnp

    from ...parallel.ring import ring_attention

    mesh = _mesh("sp")

    def fn(q, k, v):
        return ring_attention(q, k, v, mesh, causal=causal)

    qkv = tuple(_sds((1, 2, 16, 8), jnp.float32) for _ in range(3))
    return IrProgram(key=key, factory="ring_attention", anchor_path=RING,
                     jitted=jax.jit(fn), args=qkv, donate_args=())


def _build_ulysses(key: str) -> IrProgram:
    import jax
    import jax.numpy as jnp

    from ...parallel.ring import ulysses_attention

    mesh = _mesh("sp")

    def fn(q, k, v):
        return ulysses_attention(q, k, v, mesh)

    qkv = tuple(_sds((1, 2, 16, 8), jnp.float32) for _ in range(3))
    return IrProgram(key=key, factory="ulysses_attention",
                     anchor_path=RING, jitted=jax.jit(fn), args=qkv,
                     donate_args=())


def _build_tier_restore(key: str) -> IrProgram:
    import jax.numpy as jnp

    from ...kvtier.restore import make_tier_restore

    cfg = _tiny_cfg()
    fn = make_tier_restore()
    pool = (TOT, BS, cfg.n_kv_heads, cfg.head_dim)
    host = (2, BS, cfg.n_kv_heads, cfg.head_dim)  # a 2-block restore batch
    args = (_sds(pool, jnp.bfloat16), _sds(pool, jnp.bfloat16),
            _sds((2,), jnp.int32),
            _sds(host, jnp.bfloat16), _sds(host, jnp.bfloat16))
    return IrProgram(key=key, factory="make_tier_restore",
                     anchor_path="kvtier/restore.py", jitted=fn, args=args,
                     donate_args=(0, 1), compile_cpu=True)


def _build_aot_export(key: str) -> IrProgram:
    # the artifact tier: the SAME decode executable, but inspected after a
    # jax.export serialize/deserialize roundtrip — what AotCache persists
    # and a booting pod loads. Anchored at AotCache.export.
    p = _build_decode(key, feedback=False, artifact=True)
    return IrProgram(key=key, factory="AotCache.export", anchor_path=AOT,
                     jitted=p.jitted, args=p.args, donate_args=(1,),
                     artifact=True)


BUILDERS = {
    "prefill": lambda k: _build_prefill(k),
    "prefill@tp2": lambda k: _build_prefill(k, tp=True),
    "prefill_cont": lambda k: _build_prefill_cont(k),
    "decode": lambda k: _build_decode(k, feedback=False, compile_cpu=True),
    "decode_feedback": lambda k: _build_decode(k, feedback=True,
                                               compile_cpu=True),
    "decode@tp2": lambda k: _build_decode(k, feedback=False, tp=True,
                                          compile_cpu=True),
    "decode_feedback@tp2": lambda k: _build_decode(k, feedback=True,
                                                   tp=True,
                                                   compile_cpu=True),
    "decode@tp2_paged": lambda k: _build_decode(k, feedback=False, tp=True,
                                                paged=True),
    # ragged paged attention (SHAI_RAGGED_ATTENTION): full-window decode,
    # CPU leg traces the gather reference; the @tp2 leg lowers the Pallas
    # ragged kernel for the tpu platform (paged=True forces the kernel,
    # dryrun-style, like decode@tp2_paged)
    "decode_ragged": lambda k: _build_decode(k, feedback=False, ragged=True,
                                             compile_cpu=True),
    "decode_ragged@tp2": lambda k: _build_decode(k, feedback=False, tp=True,
                                                 paged=True, ragged=True),
    "prefill_rcont": lambda k: _build_rcont(k),
    "prefill_rcont@tp2": lambda k: _build_rcont(k, tp=True),
    # fused mixed-phase step (SHAI_FUSED_STEP): decode + chunk window in
    # one dispatch — donation (pool always; pos in the feedback variant)
    # and dtype drift are judged on both async disciplines, and the @tp2
    # leg lowers the flattened mixed-phase Pallas call for tpu
    "fused_step": lambda k: _build_fused(k, feedback=False,
                                         compile_cpu=True),
    "fused_step_feedback": lambda k: _build_fused(k, feedback=True,
                                                  compile_cpu=True),
    "fused_step@tp2": lambda k: _build_fused(k, feedback=False, tp=True,
                                             paged=True),
    # int8 KV pool (SHAI_KV_QUANT): the quantized scatter (prefill write),
    # the requantizing decode write + in-executable dequant reads, and the
    # scale-carrying tier restore
    "prefill_kvquant": lambda k: _build_prefill(k, kv_quant=True),
    "decode_kvquant": lambda k: _build_decode(k, feedback=False,
                                              kv_quant=True,
                                              compile_cpu=True),
    "tier_restore_quant": lambda k: _build_tier_restore_quant(k),
    "verify": lambda k: _build_verify(k),
    "cross_kv": lambda k: _build_cross_kv(k),
    "cross_slot_write": lambda k: _build_cross_slot_write(k),
    "tier_restore": lambda k: _build_tier_restore(k),
    "aot_decode_export": lambda k: _build_aot_export(k),
    "ring@sp2": lambda k: _build_ring(k, causal=False),
    "ring_causal@sp2": lambda k: _build_ring(k, causal=True),
    "ulysses@sp2": lambda k: _build_ulysses(k),
}


def build_programs(contract, keys: Optional[Tuple[str, ...]] = None
                   ) -> List[IrProgram]:
    """Build (not yet prepare) the registered programs. ``keys`` narrows
    the selection; unknown keys raise so a contract typo cannot silently
    skip a factory."""
    wanted = tuple(keys) if keys else tuple(contract.ir.programs)
    unknown = [k for k in wanted if k not in BUILDERS]
    if unknown:
        raise KeyError(
            f"unknown IR program key(s) {unknown}; registered: "
            f"{sorted(BUILDERS)}")
    return [BUILDERS[k](k) for k in wanted]
