"""jaxpr-lint: IR-level invariant checking for compiled executables.

PR 8's ``shai-lint`` checks what Python source SAYS; the bug classes that
actually hang a slice or blow an HBM budget live below the AST — XLA
silently drops a declared donation on an aval mismatch, a non-weak f32
scalar promotes a bf16 hot path, two rank programs of one TP composition
disagree on their collective schedule, a host callback serializes the
step loop, a closed-over array bloats every compiled bucket. This
package lowers (and where cheap, compiles on CPU / virtual devices) the
REGISTERED executable factories and checks five rules against the IR:

- ``program``    IrProgram: trace/lower/compile/export one factory
                 variant and expose its jaxpr, aliasing table, collective
                 schedule, consts, and callbacks
- ``factories``  the program registry: every factory the engine serves
                 with, built at tiny geometry (``contract.ir.programs``)
- ``rules``      donation-efficacy, dtype-drift, collective-schedule,
                 host-interop, baked-constants

Findings flow through the PR 8 machinery end-to-end: ``analysis.core``
Findings with rename-stable fingerprints, the inline allow grammar
anchored at the factory ``def``, the committed baseline, and the
``scripts/shai_lint.py`` CLI (``--ir``; same 0/1/2 exit contract).

Layering: this subpackage imports jax (lazily, inside functions) — it is
NOT imported by ``analysis/__init__`` or any AST checker, so plain
shai-lint still loads in milliseconds.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core import Finding
from .rules import IR_RULES  # noqa: F401


def run_ir(contract=None, keys: Optional[Tuple[str, ...]] = None,
           rules: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    """Build, prepare, and check the registered IR programs.

    ``keys`` narrows the program selection (compositions with missing
    members are skipped); ``rules`` narrows the rule set. Requires a
    jax backend with >= 2 (virtual CPU) devices for the @tp2/@sp2 legs —
    ``scripts/shai_lint.py --ir`` sets that up before importing jax.
    """
    from ..contract import DEFAULT_CONTRACT
    from . import factories, rules as irrules

    contract = contract or DEFAULT_CONTRACT
    progs = factories.build_programs(contract, keys)
    for p in progs:
        p.prepare()
    return irrules.check(progs, contract, rules)
