"""IrProgram: one executable-factory variant, traced/lowered/compiled for
inspection.

The AST checkers see what the source SAYS; this layer materializes what
the compiler actually BUILT, at tiny geometry on the CPU backend:

- ``trace`` -> the jaxpr (dtype-drift, collective-schedule, host-interop,
  baked-constants all walk it, nested sub-jaxprs included),
- ``lower`` -> the StableHLO module text (donation shows up as
  ``tf.aliasing_output`` attributes on the flattened donated inputs; a
  donation JAX dropped — aval mismatch — is a missing attribute plus a
  ``Some donated buffers were not usable`` warning, both captured here),
- ``compile`` (CPU, where cheap) -> post-optimization HLO text: the
  executable's real ``input_output_alias`` table and the collective ops
  the SPMD partitioner inserted (shard_map jaxprs only carry the
  explicit collectives; dense TP programs get theirs at compile time),
- ``export`` (artifact programs) -> the serialized ``jax.export`` module,
  the distributable analog of the reference's per-rank NEFFs — checked
  so the artifact tier cannot silently shed donation metadata.

Everything here imports jax lazily: ``analysis/`` stays importable in
milliseconds; only an explicit ``--ir`` run pays for a backend.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any, List, Optional, Tuple

#: wire collectives at jaxpr level (pbroadcast/pcast are shard_map's
#: varying-manifest bookkeeping, not communication — excluded on purpose)
JAXPR_COLLECTIVES = frozenset({
    "psum", "psum2", "ppermute", "pmax", "pmin", "pgather",
    "all_to_all", "all_gather", "all_gather_invariant",
    "reduce_scatter", "psum_scatter",
})

#: host-callback primitives: each dispatch round-trips to Python
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback",
})

#: collective op mnemonics in post-optimization HLO text
_HLO_COLLECTIVE = re.compile(
    r"=\s+(\S+)\s+(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)(?:-start)?\(")
_HLO_GROUPS = re.compile(
    r"(?:replica_groups|source_target_pairs)=(\S+?)(?:,|\s|$)")


@dataclasses.dataclass
class IrProgram:
    """One registered executable variant plus its inspection artifacts."""

    key: str                       # registry key, e.g. "decode_feedback@tp2"
    factory: str                   # factory qualname, e.g. "make_decode"
    anchor_path: str               # repo-relative file of the factory def
    jitted: Any                    # the jax.jit-wrapped callable
    args: Tuple                    # jax.ShapeDtypeStruct example arguments
    donate_args: Tuple[int, ...] = ()   # declared donated python positions
    compile_cpu: bool = False      # also compile (CPU) and cross-check
    lowering_platforms: Optional[Tuple[str, ...]] = None  # e.g. ("tpu",)
    artifact: bool = False         # jax.export roundtrip instead of lower

    # filled by prepare() (a trace/lower/compile failure propagates —
    # the CLI's documented exit-2 internal-error contract)
    jaxpr: Any = None              # ClosedJaxpr
    lowered_text: str = ""
    compiled_text: str = ""
    donation_warnings: Tuple[str, ...] = ()

    # ------------------------------------------------------------------
    def prepare(self) -> "IrProgram":
        """Trace, lower, and (per flags) compile/export the program."""
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            traced = self.jitted.trace(*self.args)
            self.jaxpr = traced.jaxpr
            if self.artifact:
                from jax import export as jexport

                exported = jexport.export(self.jitted)(*self.args)
                # what a loader pod deserializes is what we inspect
                roundtrip = jexport.deserialize(exported.serialize())
                self.lowered_text = roundtrip.mlir_module()
            else:
                if self.lowering_platforms is not None:
                    lowered = traced.lower(
                        lowering_platforms=self.lowering_platforms)
                else:
                    lowered = traced.lower()
                self.lowered_text = lowered.as_text()
                if self.compile_cpu:
                    self.compiled_text = lowered.compile().as_text()
        self.donation_warnings = tuple(
            str(w.message) for w in caught if "donated" in str(w.message))
        return self

    # -- donation ------------------------------------------------------
    def expected_donated_leaves(self) -> int:
        import jax

        return sum(len(jax.tree.leaves(self.args[i]))
                   for i in self.donate_args if i < len(self.args))

    def lowered_alias_count(self) -> int:
        return self.lowered_text.count("tf.aliasing_output")

    def compiled_alias_count(self) -> Optional[int]:
        """Entries in the executable's ``input_output_alias`` table, or
        None when the program was not compiled."""
        if not self.compiled_text:
            return None
        return len(re.findall(r"(?:may|must)-alias", self.compiled_text))

    # -- jaxpr walking -------------------------------------------------
    def all_jaxprs(self) -> List[Any]:
        """Every (sub-)jaxpr reachable from the traced program, outer
        first, deduplicated."""
        out: List[Any] = []
        seen = set()

        def add(j) -> None:
            jx = getattr(j, "jaxpr", j)
            if not hasattr(jx, "eqns") or id(jx) in seen:
                return
            seen.add(id(jx))
            out.append(j if hasattr(j, "jaxpr") else jx)
            for eq in jx.eqns:
                for v in eq.params.values():
                    if hasattr(v, "jaxpr") or hasattr(v, "eqns"):
                        add(v)
                    elif isinstance(v, (list, tuple)):
                        for e in v:
                            if hasattr(e, "jaxpr") or hasattr(e, "eqns"):
                                add(e)

        if self.jaxpr is not None:
            add(self.jaxpr)
        return out

    def all_eqns(self):
        for j in self.all_jaxprs():
            jx = getattr(j, "jaxpr", j)
            for eq in jx.eqns:
                yield jx, eq

    def all_consts(self) -> List[Any]:
        """Constants closed over by the program (outer + nested closed
        jaxprs), deduplicated by identity."""
        out: List[Any] = []
        seen = set()
        for j in self.all_jaxprs():
            for c in getattr(j, "consts", []) or []:
                if id(c) not in seen:
                    seen.add(id(c))
                    out.append(c)
        return out

    # -- collective schedules ------------------------------------------
    def jaxpr_schedule(self) -> List[Tuple[str, str, str, str]]:
        """Ordered wire collectives in the traced program:
        (primitive, axes, perm/groups, operand shapes)."""
        sched: List[Tuple[str, str, str, str]] = []
        for _, eq in self.all_eqns():
            name = eq.primitive.name
            if name not in JAXPR_COLLECTIVES:
                continue
            axes = eq.params.get("axis_name", eq.params.get("axes", ""))
            extra = eq.params.get("perm",
                                  eq.params.get("axis_index_groups", ""))
            shapes = ",".join(
                f"{v.aval.dtype}{list(v.aval.shape)}"
                for v in eq.invars if hasattr(v, "aval"))
            sched.append((name, str(axes), str(extra), shapes))
        return sched

    def compiled_schedule(self) -> Optional[List[Tuple[str, str, str]]]:
        """Ordered collective ops in the post-optimization HLO:
        (op, result type, replica groups). None when not compiled."""
        if not self.compiled_text:
            return None
        sched: List[Tuple[str, str, str]] = []
        for line in self.compiled_text.splitlines():
            m = _HLO_COLLECTIVE.search(line)
            if not m:
                continue
            g = _HLO_GROUPS.search(line)
            sched.append((m.group(2), m.group(1),
                          g.group(1) if g else ""))
        return sched

    # -- callbacks ------------------------------------------------------
    def callback_prims(self) -> List[str]:
        found = []
        for _, eq in self.all_eqns():
            if eq.primitive.name in CALLBACK_PRIMS \
                    and eq.primitive.name not in found:
                found.append(eq.primitive.name)
        return found
