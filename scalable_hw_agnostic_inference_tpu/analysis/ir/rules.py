"""The five jaxpr-lint rules, over prepared :class:`~.program.IrProgram`s.

- ``donation-efficacy``   declared ``donate_argnums`` vs the aliases the
  compiler actually established. XLA drops a donation silently when the
  donated aval matches no output (dtype/shape drift); the cost is a full
  second copy of the donated pool in HBM — for the KV pool, the largest
  single allocation in the budget — invisible until a pod OOMs.
- ``dtype-drift``         an implicit bf16→f32 promotion inside
  declared-bf16 compute: a non-weak f32 scalar (``np.float32`` config
  value, ``jnp.float32(...)`` literal) met a bf16 operand and dragged the
  op — and everything downstream of it — to f32. Explicit ``astype``
  islands (rmsnorm, logits) don't match: the rule requires the promoting
  partner to be a SCALAR, which deliberate upcasts never are.
- ``collective-schedule`` programs of one composition (the executables
  that run on the ranks of a single slice) must carry IDENTICAL ordered
  collectives — primitive, axis names, operand shapes, replica groups —
  at the jaxpr tier (explicit shard_map collectives) and, where compiled,
  in post-optimization HLO (SPMD-inserted ones). A mismatch is not an
  error message at runtime; it is a slice-wide hang.
- ``host-interop``        ``pure_callback``/``io_callback``/``debug_callback``
  (``jax.debug.print``) in a hot executable: every dispatch round-trips
  through Python, re-serializing the step loop the async pipeline exists
  to overlap.
- ``baked-constants``     closed-over arrays above the contract's size
  threshold embedded in the program: per-executable HBM the ledger's
  pool attribution can never see (it prices pools, not program bodies) —
  and one copy PER COMPILED BUCKET, not per engine.

Findings anchor at the factory ``def`` in source: the allow grammar
(``# shai-lint: allow(<rule>) <reason>`` on/above the def) and the
baseline fingerprints work exactly as for the AST rules. ``context`` is
the program key (or composition name) — path-free, rename-stable.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Module, PKG_ROOT, snippet_of
from .program import IrProgram

IR_RULES = ("donation-efficacy", "dtype-drift", "collective-schedule",
            "host-interop", "baked-constants")


# -- factory-def anchoring ----------------------------------------------------

class _Anchors:
    """Resolve (relpath, factory qualname) -> (Module, def node) once.

    ``preloaded`` lets tests inject fixture Modules for relpaths that
    don't exist under the package tree."""

    def __init__(self, preloaded: Optional[Dict[str, Module]] = None):
        self._modules: Dict[str, Module] = dict(preloaded or {})

    def module(self, relpath: str) -> Module:
        if relpath not in self._modules:
            full = os.path.join(PKG_ROOT, relpath)
            with open(full, encoding="utf-8") as f:
                self._modules[relpath] = Module(relpath, f.read())
        return self._modules[relpath]

    def node(self, relpath: str, qualname: str):
        import ast

        mod = self.module(relpath)
        scope = mod.tree
        parts = qualname.split(".")
        for i, part in enumerate(parts):
            nxt = None
            for child in ast.iter_child_nodes(scope):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)) \
                        and child.name == part:
                    nxt = child
                    break
            if nxt is None:
                return None
            scope = nxt
        return scope


def _finding(anchors: _Anchors, prog: IrProgram, rule: str, context: str,
             message: str) -> Finding:
    mod = anchors.module(prog.anchor_path)
    node = anchors.node(prog.anchor_path, prog.factory)
    line = getattr(node, "lineno", 0)
    allowed, reason, problem = (False, "", None)
    if node is not None:
        # the rule's own name, or the umbrella token allow(ir)
        allowed, reason, problem = mod.allow_at(node, rule)
        if not allowed and problem is None:
            allowed, reason, problem = mod.allow_at(node, "ir")
    if problem:
        message += f" ({problem})"
    return Finding(
        rule=rule, path=prog.anchor_path, line=line, context=context,
        message=message, allowed=allowed, reason=reason,
        snippet=snippet_of(mod, node) if node is not None else "")


# -- the rules ----------------------------------------------------------------

def check_donation(progs: List[IrProgram], contract, anchors: _Anchors
                   ) -> List[Finding]:
    findings: List[Finding] = []
    for p in progs:
        expected = p.expected_donated_leaves()
        actual = p.lowered_alias_count()
        if actual < expected:
            detail = ""
            if p.donation_warnings:
                detail = (" — the compiler said: "
                          + "; ".join(sorted(set(p.donation_warnings))))
            where = ("the exported artifact"
                     if p.artifact else "the lowered module")
            findings.append(_finding(
                anchors, p, "donation-efficacy", p.key,
                f"{actual} of {expected} declared donated buffers are "
                f"aliased in {where} — each dropped donation "
                f"double-buffers its pool in HBM{detail}"))
        elif actual > expected:
            findings.append(_finding(
                anchors, p, "donation-efficacy", p.key,
                f"{actual} aliased buffers but only {expected} declared "
                f"donated leaves — the declared donation contract is "
                f"stale; update donate_args for this program"))
        compiled = p.compiled_alias_count()
        if compiled is not None and compiled < actual:
            findings.append(_finding(
                anchors, p, "donation-efficacy", p.key,
                f"the compiled executable's input_output_alias table has "
                f"{compiled} entries but lowering established {actual} — "
                f"XLA dropped donations at compile time (layout "
                f"mismatch class)"))
    return findings


#: user-facing conversion entry points: a convert whose traceback passes
#: through one of these was WRITTEN, not inserted by type promotion
_EXPLICIT_CONVERT_FRAMES = frozenset({
    "astype", "_astype", "convert_element_type", "asarray", "_asarray",
    "array",
})


def _is_explicit_convert(eq) -> bool:
    tb = getattr(getattr(eq, "source_info", None), "traceback", None)
    if tb is None:
        return False  # no provenance: treat as implicit (conservative)
    try:
        return any(fr.function_name in _EXPLICIT_CONVERT_FRAMES
                   for fr in tb.frames)
    except Exception:
        return False


def check_dtype_drift(progs: List[IrProgram], contract, anchors: _Anchors
                      ) -> List[Finding]:
    import jax.core as jcore

    findings: List[Finding] = []
    declared = set(contract.ir.bf16_programs)
    for p in progs:
        if p.key not in declared and "*" not in declared:
            continue
        hit_prims: List[str] = []
        for j in p.all_jaxprs():
            jx = getattr(j, "jaxpr", j)
            converted = set()
            for eq in jx.eqns:
                if eq.primitive.name == "convert_element_type":
                    iv = eq.invars[0]
                    if hasattr(iv, "aval") \
                            and str(iv.aval.dtype) == "bfloat16" \
                            and str(eq.outvars[0].aval.dtype) == "float32" \
                            and not _is_explicit_convert(eq):
                        converted.add(eq.outvars[0])
                    continue
                uses_conv = any(
                    (not isinstance(v, jcore.Literal)) and v in converted
                    for v in eq.invars)
                if not uses_conv:
                    continue
                for other in eq.invars:
                    av = getattr(other, "aval", None)
                    if av is None or str(av.dtype) != "float32":
                        continue
                    if av.shape == () and not getattr(av, "weak_type", True):
                        if eq.primitive.name not in hit_prims:
                            hit_prims.append(eq.primitive.name)
                        break
        for prim in hit_prims:
            findings.append(_finding(
                anchors, p, "dtype-drift", p.key,
                f"implicit bf16->f32 promotion at `{prim}`: a non-weak "
                f"f32 scalar met bf16 compute and upcast it — the hot "
                f"path runs (and writes) f32 from here on; wrap the "
                f"scalar as a python float or .astype the intent "
                f"explicitly"))
    return findings


def check_collectives(progs: List[IrProgram], contract, anchors: _Anchors
                      ) -> List[Finding]:
    findings: List[Finding] = []
    by_key = {p.key: p for p in progs}
    for comp, members in sorted(contract.ir.compositions.items()):
        built = [by_key[m] for m in members if m in by_key]
        if len(built) < 2:
            continue  # subset run: composition not comparable
        base = built[0]
        base_sched = base.jaxpr_schedule()
        for other in built[1:]:
            sched = other.jaxpr_schedule()
            diff = _first_divergence(base_sched, sched)
            if diff is not None:
                i, a, b = diff
                findings.append(_finding(
                    anchors, other, "collective-schedule", comp,
                    f"collective schedules diverge between `{base.key}` "
                    f"and `{other.key}` at collective #{i}: "
                    f"{a or 'end-of-schedule'} vs {b or 'end-of-schedule'}"
                    f" — rank-mismatched collectives hang the slice"))
        scheds = [(p, p.compiled_schedule()) for p in built]
        if all(s is not None for _, s in scheds):
            base_p, base_s = scheds[0]
            for other_p, other_s in scheds[1:]:
                diff = _first_divergence(base_s, other_s)
                if diff is not None:
                    i, a, b = diff
                    findings.append(_finding(
                        anchors, other_p, "collective-schedule", comp,
                        f"compiled (SPMD-inserted) collective schedules "
                        f"diverge between `{base_p.key}` and "
                        f"`{other_p.key}` at collective #{i}: "
                        f"{a or 'end-of-schedule'} vs "
                        f"{b or 'end-of-schedule'}"))
    return findings


def _first_divergence(a: List, b: List
                      ) -> Optional[Tuple[int, object, object]]:
    for i in range(max(len(a), len(b))):
        ea = a[i] if i < len(a) else None
        eb = b[i] if i < len(b) else None
        if ea != eb:
            return i, ea, eb
    return None


def check_host_interop(progs: List[IrProgram], contract, anchors: _Anchors
                       ) -> List[Finding]:
    findings: List[Finding] = []
    hot = set(contract.ir.hot_programs)
    for p in progs:
        if p.key not in hot and "*" not in hot:
            continue
        for prim in p.callback_prims():
            findings.append(_finding(
                anchors, p, "host-interop", p.key,
                f"host callback `{prim}` inside a hot executable — every "
                f"dispatch round-trips through Python, serializing the "
                f"step loop (jax.debug.print lowers to debug_callback)"))
    return findings


def check_baked_constants(progs: List[IrProgram], contract,
                          anchors: _Anchors) -> List[Finding]:
    findings: List[Finding] = []
    limit = contract.ir.const_limit_bytes
    for p in progs:
        seen = set()
        for c in p.all_consts():
            nbytes = getattr(c, "nbytes", 0)
            if nbytes <= limit:
                continue
            shape = tuple(getattr(c, "shape", ()))
            dtype = str(getattr(c, "dtype", type(c).__name__))
            ident = (dtype, shape)
            if ident in seen:
                continue
            seen.add(ident)
            findings.append(_finding(
                anchors, p, "baked-constants", p.key,
                f"constant {dtype}{list(shape)} ({nbytes} bytes > "
                f"{limit} limit) is baked into the program body — "
                f"per-executable HBM the ledger's pool attribution "
                f"cannot see, one copy per compiled bucket"))
    return findings


def check(progs: List[IrProgram], contract,
          rules: Optional[Tuple[str, ...]] = None,
          modules: Optional[Dict[str, Module]] = None) -> List[Finding]:
    """Run the (selected) IR rules over prepared programs."""
    anchors = _Anchors(modules)
    selected = set(rules) if rules else set(IR_RULES)
    findings: List[Finding] = []
    if "donation-efficacy" in selected:
        findings += check_donation(progs, contract, anchors)
    if "dtype-drift" in selected:
        findings += check_dtype_drift(progs, contract, anchors)
    if "collective-schedule" in selected:
        findings += check_collectives(progs, contract, anchors)
    if "host-interop" in selected:
        findings += check_host_interop(progs, contract, anchors)
    if "baked-constants" in selected:
        findings += check_baked_constants(progs, contract, anchors)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
