"""Env-knob registry checker: parse leniently, read centrally, document.

PR 7's review found ``SHAI_HBM_WINDOW=8.5`` crash-looping engine
construction through a bare ``int()`` — a malformed TUNING knob must
degrade to its default, never take a serving tier down at boot. The
lenient parsers (``obs/util.py``, re-exported through ``utils/env.py``)
fixed that for ``obs/``; this checker generalizes the rule to the whole
package, three sub-rules:

- ``env-parse``: a raw env read wrapped in ``int(...)``/``float(...)`` —
  the boot-crash-loop class. Use ``env_int``/``env_float``.
- ``env-read``: any direct ``os.environ``/``os.getenv`` access outside
  the parser modules. Reads go through the parser seam
  (``env_str``/``env_flag`` for strings/gates) so the knob registry stays
  complete; deliberate raw reads carry a declared exemption
  (``contract.env_exempt_*``) or ``# shai-lint: allow(env-knob) reason``.
- ``env-doc``: every knob name the package reads — collected from read
  sites, parser calls, and every ``SHAI_*`` string literal — must appear
  in README.md (the operator contract; subsumes the metric-docs gate's
  approach for env vars).
- ``env-deploy``: every ``SHAI_*`` name a K8s manifest under ``deploy/``
  sets must be one the code actually reads — a typo'd knob in YAML
  parses, applies, and silently no-ops today; this makes it a finding.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, resolved_dotted, snippet_of, str_arg

#: a SHAI_* knob name anywhere in source (docstrings/comments included —
#: if the code talks about it, the operator doc must too)
_SHAI_NAME = re.compile(r"\bSHAI_[A-Z0-9_]+\b")

_READ_FUNCS = {"os.environ.get", "os.getenv"}


def _env_read_name(module: Module, node: ast.AST) -> Optional[Tuple[str,
                                                                    bool]]:
    """(env name or "<dynamic>", is_read) when ``node`` reads the
    process environment directly."""
    if isinstance(node, ast.Call):
        d = resolved_dotted(module, node.func)
        if d in _READ_FUNCS and node.args:
            return (str_arg(module, node.args[0]) or "<dynamic>", True)
    if isinstance(node, ast.Subscript) \
            and isinstance(getattr(node, "ctx", None), ast.Load):
        d = resolved_dotted(module, node.value)
        if d == "os.environ":
            return (str_arg(module, node.slice) or "<dynamic>", True)
    return None


def _wrapped_in_cast(node: ast.AST) -> Optional[str]:
    """"int"/"float" when an ancestor call casts this read's value within
    the same expression."""
    cur = getattr(node, "_shai_parent", None)
    while cur is not None and not isinstance(cur, ast.stmt):
        if isinstance(cur, ast.Call) and isinstance(cur.func, ast.Name) \
                and cur.func.id in ("int", "float"):
            return cur.func.id
        cur = getattr(cur, "_shai_parent", None)
    return None


def check(modules: List[Module], contract, readme_text: str,
          deploy_names: Optional[Dict[str, Tuple[str, int]]] = None
          ) -> List[Finding]:
    findings: List[Finding] = []
    #: name -> first (path, line) that reads it (doc check anchor)
    registered: Dict[str, Tuple[str, int]] = {}

    for module in modules:
        path = module.relpath
        for m in _SHAI_NAME.finditer(module.source):
            name = m.group(0)
            line = module.source.count("\n", 0, m.start()) + 1
            registered.setdefault(name, (path, line))
        # lenient-parser calls register their knob for the doc check —
        # in EVERY module, parser modules included (ServeConfig.from_env
        # lives in utils/env.py and its knobs are part of the registry)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                tail = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else "")
                if tail in contract.env_parser_names and node.args:
                    name = str_arg(module, node.args[0])
                    if name:
                        registered.setdefault(name, (path, node.lineno))
        if path in contract.env_parser_modules \
                or path in contract.env_exempt_modules:
            continue
        for node in ast.walk(module.tree):
            got = _env_read_name(module, node)
            if got is None:
                continue
            name, _ = got
            if name != "<dynamic>":
                registered.setdefault(name, (path, node.lineno))
            exempt_reason = contract.env_exempt_sites.get((path, name))
            cast = _wrapped_in_cast(node)
            # the umbrella token allow(env-knob) covers both sub-rules; an
            # annotation naming the finding's own rule works too
            sub_rule = "env-parse" if cast is not None else "env-read"
            allowed, reason, problem = module.allow_at(node, "env-knob")
            if not allowed and problem is None:
                allowed, reason, problem = module.allow_at(node, sub_rule)
            if cast is not None:
                msg = (f"raw env read cast through {cast}() — a malformed "
                       f"value crash-loops boot; use the lenient "
                       f"env_{cast} parser")
                if problem:
                    msg += f" ({problem})"
                findings.append(Finding(
                    rule="env-parse", path=path, line=node.lineno,
                    context=name, message=msg,
                    allowed=allowed or exempt_reason is not None,
                    reason=reason or (exempt_reason or ""),
                    snippet=snippet_of(module, node)))
            else:
                msg = ("direct environment read bypasses the parser seam "
                       "(obs/util.py, utils/env.py)")
                if problem:
                    msg += f" ({problem})"
                findings.append(Finding(
                    rule="env-read", path=path, line=node.lineno,
                    context=name, message=msg,
                    allowed=allowed or exempt_reason is not None,
                    reason=reason or (exempt_reason or ""),
                    snippet=snippet_of(module, node)))

    for name in sorted(registered):
        if name in contract.env_doc_exempt or name in readme_text:
            continue
        path, line = registered[name]
        findings.append(Finding(
            rule="env-doc", path=path, line=line, context=name,
            message=("env knob is read/declared in code but absent from "
                     "README.md — document it in the environment-knob "
                     "registry")))

    # manifests may only set names the code reads: a typo'd SHAI_ knob in
    # YAML is accepted by the cluster and ignored by every pod
    for name in sorted(deploy_names or {}):
        if name in registered or name in contract.env_doc_exempt:
            continue
        path, line = deploy_names[name]
        findings.append(Finding(
            rule="env-deploy", path=path, line=line, context=name,
            message=("env knob is set in a deploy manifest but no code "
                     "reads it — a typo'd name here silently no-ops")))
    return findings
