"""THE declared invariant tables shai-lint checks the tree against.

Every checker reads its ground truth from here, not from heuristics buried
in checker code: which functions are the decode hot path, which callables
donate which argument positions, which attributes of which classes are
loop-thread-only / lock-guarded / immutable-after-init, which env reads
are deliberately strict, which GET routes are poll surfaces. Changing an
invariant is a one-line diff in this file — reviewed as a contract change,
not an incidental checker tweak.

Tests override :data:`DEFAULT_CONTRACT` with fixture-sized tables via
``dataclasses.replace``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    """Concurrency contract for one class's attributes.

    Any attribute not listed in ``immutable_after_init`` or
    ``lock_guarded`` is *owner-thread-only* mutable state: it may be
    written only from ``owning_modules`` (for the engine: code that runs
    on the engine-loop thread).
    """

    #: attrs bound in __init__ (or a declared init method) and never again
    immutable_after_init: Tuple[str, ...] = ()
    #: methods that count as construction time (lock/immutability exempt)
    init_methods: Tuple[str, ...] = ("__init__",)
    #: attr -> the ``self.<lock>`` a write site must hold lexically
    lock_guarded: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: repo-relative modules allowed to write the mutable attrs
    owning_modules: Tuple[str, ...] = ()
    #: dotted-path markers identifying an instance at a write site OUTSIDE
    #: the class body (e.g. ``engine.`` / ``eng.`` locals, ``.engine.``
    #: attribute chains). Checked as a prefix or infix of the write path.
    instance_markers: Tuple[str, ...] = ()
    #: lock attributes this class OWNS. Each becomes a lock IDENTITY
    #: ``"<Class>.<attr>"`` in the shai-race acquisition graph
    #: (``analysis/race.py``); defaults to the distinct values of
    #: ``lock_guarded`` when empty, so a class whose only lock guards
    #: attributes needs no duplicate declaration.
    locks: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class RaceSpec:
    """Declared tables for the shai-race pass (``analysis/race.py``).

    Lock IDENTITIES are ``"<Class>.<attr>"`` for locks owned by a
    ``thread_contract`` class (``ClassPolicy.locks`` /
    ``lock_guarded`` values, resolved through ``self.<attr>`` inside the
    class body and through ``instance_markers`` outside it) plus the
    module-scope ids declared in :attr:`module_locks` (closure locks
    like ``serve.app``'s ``inflight_lock``).
    """

    #: module relpath -> {with-target dotted name: lock identity} for
    #: locks that live in closures / module scope rather than on a
    #: contract class
    module_locks: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)
    #: lock identities declared HOT: a blocking call (queue get/put,
    #: Future.result, Thread.join, Event.wait, time.sleep, sockets,
    #: device fetches) lexically under one of these is a finding —
    #: every thread in the process eventually serializes behind them
    hot_locks: Tuple[str, ...] = ()
    #: the allowed partial order: ``(outer, inner)`` means "``outer`` may
    #: be held while acquiring ``inner``". Every observed cross-lock
    #: acquisition edge must appear here (transitively); an edge whose
    #: REVERSE is derivable, or that is simply undeclared, is a finding.
    #: The declared set itself must be acyclic — checked every run.
    lock_order: Tuple[Tuple[str, str], ...] = ()


@dataclasses.dataclass(frozen=True)
class IrSpec:
    """Declared tables for the jaxpr-lint IR pass (``analysis/ir/``).

    The AST layer checks what Python source says; this layer checks what
    the COMPILED programs actually are. ``programs`` names registry keys
    resolved by ``analysis/ir/factories.py`` — each key builds one
    executable variant (tiny config, CPU/virtual-device mesh) and lowers
    (where cheap, compiles) it. Keys carry their geometry in the name
    (``decode_feedback@tp2``) so a finding names the exact variant.
    """

    #: registry keys analysis/ir/factories.py knows how to build; the IR
    #: pass builds and checks every one of these
    programs: Tuple[str, ...] = ()
    #: program keys whose compute is declared bf16 — dtype-drift applies
    bf16_programs: Tuple[str, ...] = ()
    #: program keys that are decode-hot — host-interop applies (a
    #: pure_callback in a hot executable serializes every step)
    hot_programs: Tuple[str, ...] = ()
    #: composition name -> program keys whose collective schedules must be
    #: IDENTICAL (primitive, axis names, shapes, replica groups, order) —
    #: divergence between programs that run on the ranks of one slice is
    #: a runtime hang, not an error message
    compositions: Dict[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    #: bytes above which a constant baked into a program body is a
    #: finding (per-executable HBM bloat the HBM ledger cannot attribute)
    const_limit_bytes: int = 1 << 16


@dataclasses.dataclass(frozen=True)
class Contract:
    # -- host-sync: declared decode hot paths ------------------------------
    #: repo-relative file -> qualnames whose bodies (nested defs included)
    #: must not synchronize device->host. "*" = every function in the file.
    hot_paths: Dict[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict)

    # -- donation ----------------------------------------------------------
    #: files scanned for ``jax.jit(fn, donate_argnums=...)`` factory defs
    donation_factory_files: Tuple[str, ...] = ()
    #: files whose call sites are checked for donated-read-after-dispatch
    donation_check_files: Tuple[str, ...] = ()
    #: method name -> (factory name, index of the executable in the
    #: accessor's returned tuple; None = the whole return value). Example:
    #: ``_decode_for`` returns ``(batch_bucket, decode_fn)`` built by
    #: ``make_decode`` -> ("make_decode", 1).
    accessor_factories: Dict[str, Tuple[str, Optional[int]]] = (
        dataclasses.field(default_factory=dict))
    #: function qualname -> {parameter name: factory name} for executables
    #: passed in as arguments (the dispatch helpers)
    param_factories: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)
    #: instance-attribute callables built by a factory (``self._cross_write``)
    attr_factories: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: method name -> 0-based positional indices (self excluded) whose
    #: argument buffers the method donates onward
    donating_calls: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict)

    # -- thread discipline -------------------------------------------------
    thread_contract: Dict[str, ClassPolicy] = dataclasses.field(
        default_factory=dict)
    #: module -> {dict var name: (guarded keys, lock name)} for
    #: closure-state dicts (serve.app's ``state``)
    dict_guards: Dict[str, Dict[str, Tuple[Tuple[str, ...], str]]] = (
        dataclasses.field(default_factory=dict))

    # -- env knobs ---------------------------------------------------------
    #: the modules that OWN raw env reads (the parser seam itself)
    env_parser_modules: Tuple[str, ...] = ()
    #: modules exempt from the env rules entirely (with the reason)
    env_exempt_modules: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    #: (file, env name) -> reason: declared strict-parse/raw-read exemptions
    env_exempt_sites: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict)
    #: lenient parser helpers (calls to these register the name, satisfy
    #: the read rule, and are doc-checked)
    env_parser_names: Tuple[str, ...] = (
        "env_int", "env_float", "env_str", "env_bool", "env_flag")
    #: env names that need no README entry (platform/infra, not knobs)
    env_doc_exempt: Tuple[str, ...] = ()

    # -- trace exclusion ---------------------------------------------------
    #: files defining the app surface: routes + trace_exclude literals
    trace_files: Tuple[str, ...] = ()
    #: GET routes (beyond /debug/*) that are poll surfaces and must be
    #: excluded from the flight-recorder trace ring
    poll_routes: Tuple[str, ...] = ()

    # -- race pass (shai-race) ---------------------------------------------
    race: RaceSpec = dataclasses.field(default_factory=RaceSpec)

    # -- IR pass (jaxpr-lint) ----------------------------------------------
    ir: IrSpec = dataclasses.field(default_factory=IrSpec)


#: the live tree's contract ---------------------------------------------------

DEFAULT_CONTRACT = Contract(
    # The async decode hot loop (PR 6): the steady path dispatches step N+1
    # before retiring step N — any host synchronization here serializes the
    # pipeline and silently reverts the 1.4x async win. _retire_pipe is ON
    # this list although it contains the one intentional blocking fetch:
    # that fetch is documented via the allow grammar, not exempted.
    hot_paths={
        "engine/engine.py": (
            "LLMEngine._step_async",
            "LLMEngine._steady_step",
            "LLMEngine._decode_dispatch",
            "LLMEngine._dispatch_async",
            "LLMEngine._retire_pipe",
            # the QoS weighted-fair dequeue runs on every admission step:
            # it must stay pure host arithmetic — a device sync here would
            # serialize admission behind the decode pipeline
            "LLMEngine._schedule_head",
        ),
        # the scheduler kernel itself (stride select + head rotation):
        # same discipline, shared by the engine and the property tests
        "resilience/qos.py": (
            "WeightedFairScheduler.select", "schedule_rotate"),
        "engine/resident.py": ("*",),
        # the jitted decode/verify bodies: a host sync here would be a
        # trace-time crash on device — and on CPU fallbacks a silent
        # per-step serialization
        "engine/runner.py": (
            "make_decode", "make_verify", "make_fused_step",
            "_make_token_forward"),
        # the KV-tier movers' jitted bodies: a host sync traced into the
        # demotion gather or restore scatter would serialize every
        # eviction/warm-hit on the host (same discipline as runner.py)
        "kvtier/restore.py": ("make_tier_gather", "make_tier_restore"),
        # the autoscaler's decision kernel and tick: pure host arithmetic
        # by contract — the control loop must never block on a device (a
        # sync here would couple scaling cadence to decode dispatch)
        "orchestrate/scaler.py": (
            "Scaler._decide_pool", "Scaler.tick", "Scaler.run_tick"),
    },
    donation_factory_files=("engine/runner.py", "core/aot.py",
                            "kvtier/restore.py"),
    donation_check_files=(
        "engine/engine.py", "engine/runner.py", "engine/warm.py",
        "engine/cross.py", "core/aot.py", "engine/cache.py",
        "kvtier/restore.py", "kvtier/pool.py"),
    accessor_factories={
        "_prefill_for": ("make_prefill", None),
        "_cont_for": ("make_prefill_cont", None),
        "_decode_for": ("make_decode", 1),
        "_verify_for": ("make_verify", 1),
        # the fused mixed-phase ladder (SHAI_FUSED_STEP): one executable
        # per batch bucket, returned as (batch_bucket, fused_fn)
        "_fused_for": ("make_fused_step", 1),
    },
    param_factories={
        # the async dispatch helper receives the compiled decode executable
        "LLMEngine._dispatch_async": {"decode": "make_decode"},
    },
    attr_factories={"_cross_write": "make_cross_slot_write",
                    # the cache's restore scatter (donate-and-rebind per
                    # layer) and demotion gather (no donation)
                    "_tier_restore": "make_tier_restore",
                    "_tier_gather": "make_tier_gather"},
    donating_calls={
        # _dispatch_async(decode, running, Bb, tokens_dev, pos_dev, a, rng):
        # pos_dev (index 4) is donated into the feedback-decode dispatch
        # (tokens_dev is NOT — the host reads it back one step later)
        "_dispatch_async": (4,),
    },
    thread_contract={
        # The engine is single-threaded by design: ONE loop thread owns it;
        # the serve lane reaches it only through EngineLoop's queues. Any
        # attribute write from outside the owning modules is a cross-thread
        # mutation of unlocked state.
        "LLMEngine": ClassPolicy(
            immutable_after_init=(
                "cfg", "ecfg", "params", "cross_seq_len", "shardings",
                "cache", "buckets", "_chunk_cap", "_ctx_buckets",
                "_drafter", "spec", "_spec_rng", "_sample1", "_lp1",
                "_cross_embed", "_cross_write", "ttft", "tpot", "obs",
                "_hbm_every", "_hbm_dev", "_async", "_ids", "_res",
                "_ragged", "_kv_quant", "_fused", "_kv_cow", "role",
                "_prefill_role"),
            owning_modules=(
                "engine/engine.py", "engine/warm.py", "engine/cross.py",
                "engine/logprobs.py", "engine/speculative.py",
                "engine/loop.py"),
            instance_markers=("engine.", "eng."),
        ),
        "ResidentBatch": ClassPolicy(
            owning_modules=("engine/resident.py", "engine/engine.py"),
            instance_markers=("._res.",),
        ),
        # EngineLoop bridges the serve lane and the loop thread: the
        # futures table is the one cross-thread structure, guarded by
        # _futures_lock at every mutation site.
        "EngineLoop": ClassPolicy(
            immutable_after_init=("engine", "_poll_s", "_submit_q",
                                  "_cancel_q", "_futures_lock", "_stop",
                                  "_draining", "_thread",
                                  "_migrate_evt", "_migrate_done"),
            lock_guarded={"_futures": "_futures_lock"},
            owning_modules=("engine/loop.py",),
            instance_markers=(".loop.",),
        ),
        # The flight ring takes writes from every request thread.
        "FlightRecorder": ClassPolicy(
            immutable_after_init=("max_requests", "max_steps", "_lock"),
            lock_guarded={"_requests": "_lock", "_seq": "_lock",
                          "_by_trace": "_lock"},
            owning_modules=("obs/flight.py",),
        ),
        # The step telemetry is written by the engine-loop thread and read
        # by scrape/dump threads: the container attrs (step ring, gauge
        # dict, tenant tables) are lock-guarded on BOTH sides — the
        # guarded-read rule is what catches a torn /stats snapshot.
        # Scalar counters (steps, preemptions, ...) stay undeclared: a
        # torn int read cannot exist under the GIL and declaring them
        # would bury the structural reads in noise.
        "StepTelemetry": ClassPolicy(
            immutable_after_init=("ttft", "tpot", "queue_wait", "step_gap",
                                  "_lock"),
            lock_guarded={"_steps": "_lock", "_gauges": "_lock",
                          "_tenants": "_lock", "_tenant_ttft": "_lock",
                          "_flush_reasons": "_lock"},
            owning_modules=("obs/steploop.py",),
        ),
        # The admission gate's shed counters take writes from every
        # request thread and reads from /stats scrapes.
        "AdmissionGate": ClassPolicy(
            immutable_after_init=(
                "thresholds", "max_inflight", "retry_after_s",
                "drain_retry_after_s", "ledger", "tenant_max_inflight",
                "tier_full_utilization", "tier_full_kv_utilization",
                "_lock"),
            lock_guarded={"_shed": "_lock"},
            owning_modules=("resilience/admission.py",),
            instance_markers=("gate.", ".gate."),
        ),
        # The drain flag is armed by the SIGTERM handler and read by every
        # admission/readiness path.
        "DrainController": ClassPolicy(
            immutable_after_init=("budget_s", "_clock", "_lock"),
            lock_guarded={"_started_at": "_lock"},
            owning_modules=("resilience/drain.py",),
            instance_markers=("drainer.", ".drainer."),
        ),
        # The host KV tier is written from TWO threads by design: the
        # engine thread stores/probes/restores, the copy-out worker
        # publishes materialized entries — every mutation of the entry
        # map and the counters moves under _lock.
        "HostKVTier": ClassPolicy(
            immutable_after_init=(
                "n_layers", "block_size", "n_kv_heads", "head_dim",
                "dtype", "block_nbytes", "capacity_bytes", "async_copy",
                "_lock"),
            lock_guarded={"_entries": "_lock", "_stats": "_lock",
                          "_closing": "_lock"},
            owning_modules=("kvtier/pool.py",),
            instance_markers=(".tier.",),
        ),
        # The copy-out worker's queue/thread bindings are fixed at
        # construction; the queue object itself is the cross-thread seam.
        "CopyOutWorker": ClassPolicy(
            immutable_after_init=("_pool", "_q", "_thread", "_closed",
                                  "_sub_lock"),
            locks=("_sub_lock",),
            owning_modules=("kvtier/pool.py",),
        ),
        # The kvnet transport counters take writes from lane threads (the
        # decode-role fetch) AND the event loop (the /kv/blocks serve
        # side), reads from scrape threads — all under _lock.
        "KvNetStats": ClassPolicy(
            immutable_after_init=("_lock",),
            lock_guarded={"_counts": "_lock"},
            owning_modules=("kvnet/client.py",),
        ),
        # The kvnet client is shared by every serving-lane thread: the
        # lazily-built httpx client and the per-peer breaker table move
        # under _lock; the HTTP call itself runs OUTSIDE it (the
        # blocking-under-lock rule is what enforces that stays true).
        "KvNetClient": ClassPolicy(
            immutable_after_init=(
                "tier", "stats", "timeout_s", "connect_timeout_s",
                "connect_retries", "allowed_peers", "_breaker_factory",
                "_transport", "_lock"),
            lock_guarded={"_client": "_lock", "_breakers": "_lock"},
            owning_modules=("kvnet/client.py",),
        ),
        # Live migration (kvnet/migrate.py): the counters take writes
        # from the drain thread (ship), the event loop (accept), and
        # lane threads (resume); the inbox takes puts from the accept
        # path and pops from replay lanes — all under their _lock. The
        # SNAPSHOT itself happens on the engine loop thread; the SHIP
        # runs on a serving thread outside every declared lock (the
        # hot_locks entries below make blocking-under-lock enforce that
        # mechanically — the PR-14 httpx-under-lock lesson).
        "MigrateStats": ClassPolicy(
            immutable_after_init=("_lock",),
            lock_guarded={"_counts": "_lock"},
            owning_modules=("kvnet/migrate.py",),
        ),
        "MigrationInbox": ClassPolicy(
            immutable_after_init=("capacity", "_lock"),
            lock_guarded={"_entries": "_lock", "_accepting": "_lock"},
            owning_modules=("kvnet/migrate.py",),
        ),
        # KV fabric (kvnet/directory.py): counters take writes from the
        # engine loop (probe outcomes) and lane threads (replication
        # pulls); the directory takes updates from whoever polls peers
        # and reads from the probe path — every map under _lock, every
        # HTTP fetch outside it (the hot_locks entries enforce that).
        "KvFabricStats": ClassPolicy(
            immutable_after_init=("_lock",),
            lock_guarded={"_counts": "_lock"},
            owning_modules=("kvnet/directory.py",),
        ),
        "KvDirectory": ClassPolicy(
            immutable_after_init=("ttl_s", "_lock"),
            lock_guarded={"_holders": "_lock", "_by_holder": "_lock",
                          "_hits": "_lock", "_aff2head": "_lock"},
            owning_modules=("kvnet/directory.py",),
        ),
        # The probe's own lock guards ONLY the refresh deadline — the
        # digest fetches and the run pull run outside it by contract.
        "FabricProbe": ClassPolicy(
            immutable_after_init=("tier", "stats", "client", "peers",
                                  "ttl_s", "directory", "_lock"),
            lock_guarded={"_refresh_at": "_lock"},
            owning_modules=("kvnet/directory.py",),
        ),
        # The tenant ledger takes writes from every serving thread
        # (admission checks, completion charges) and reads from scrape
        # threads: bucket state and per-tenant counters move under _lock
        # at every mutation site.
        "TenantLedger": ClassPolicy(
            immutable_after_init=("budgets", "default_budget",
                                  "max_tenants", "_clock", "_lock"),
            lock_guarded={"_buckets": "_lock", "_stats": "_lock"},
            owning_modules=("resilience/qos.py",),
            instance_markers=(".ledger.", "led."),
        ),
        # The scheduler is engine-loop-thread-only by contract (select()
        # mutates stride state); only the engine and the qos module may
        # touch it.
        "WeightedFairScheduler": ClassPolicy(
            immutable_after_init=("weights", "aging_rounds"),
            owning_modules=("resilience/qos.py", "engine/engine.py"),
            instance_markers=("sched.",),
        ),
        # The autoscaler: decision counters take writes from the control
        # tick and reads from scrape threads; pool state moves only under
        # the scaler's own lock. The apply callback (drain/spawn, which
        # may block on HTTP) runs OUTSIDE both by contract — the
        # hot_locks entries enforce that mechanically.
        "ScalerStats": ClassPolicy(
            immutable_after_init=("_lock",),
            lock_guarded={"_counts": "_lock"},
            owning_modules=("orchestrate/scaler.py",),
        ),
        "Scaler": ClassPolicy(
            immutable_after_init=("cfg", "pricer", "stats", "clock",
                                  "_lock"),
            lock_guarded={"_pools": "_lock"},
            owning_modules=("orchestrate/scaler.py",),
        ),
        # Request reliability (PR 20): the idempotency cache takes writes
        # from every keyed lane thread and reads from scrapes; joiners
        # park on per-entry events strictly OUTSIDE the lock.
        "IdempotencyCache": ClassPolicy(
            immutable_after_init=("max_entries", "ttl_s", "_clock",
                                  "_lock"),
            lock_guarded={"_entries": "_lock", "_counts": "_lock"},
            owning_modules=("resilience/idempotency.py",),
            instance_markers=("idem.", ".idem"),
        ),
        # cova's hedge/budget/poison state is shared between the async
        # dispatch path and scrape threads; every mutation is a leaf
        # under the instance lock — the hot_locks entries below keep
        # httpx (and anything else blocking) out from under them.
        "RetryBudget": ClassPolicy(
            immutable_after_init=("pct", "burst", "window", "_lock"),
            lock_guarded={"_tokens": "_lock", "_counts": "_lock"},
            owning_modules=("resilience/hedge.py",),
        ),
        "HedgeGovernor": ClassPolicy(
            immutable_after_init=("default_s", "min_s", "max_s",
                                  "min_samples", "_lock"),
            lock_guarded={"_lat": "_lock"},
            owning_modules=("resilience/hedge.py",),
        ),
        "PoisonRegistry": ClassPolicy(
            immutable_after_init=("k", "max_entries", "_lock"),
            lock_guarded={"_counts": "_lock", "_stats": "_lock"},
            owning_modules=("resilience/hedge.py",),
        ),
        "HedgeStats": ClassPolicy(
            immutable_after_init=("_lock",),
            lock_guarded={"_counts": "_lock",
                          "_follow_depth_max": "_lock"},
            owning_modules=("resilience/hedge.py",),
        ),
    },
    dict_guards={
        # serve.app closure state shared between the event loop and lane/
        # stream threads: the in-flight counters must move under the lock
        "serve/app.py": {
            "state": (("inflight", "lane_pending"), "inflight_lock"),
        },
    },
    env_parser_modules=("obs/util.py", "utils/env.py"),
    env_exempt_modules={
        "perf/topo.py": "env snapshot/restore helper — sets and restores "
                        "arbitrary entries around subprocess topology "
                        "probes; it parses nothing",
    },
    env_exempt_sites={},
    env_doc_exempt=(
        # platform/infra variables owned by JAX/XLA or the test harness,
        # not operator-facing serving knobs
        "XLA_FLAGS", "JAX_DEFAULT_DEVICE", "JAX_PLATFORMS",
        "ALLOW_MULTIPLE_LIBTPU_LOAD", "SHAI_TEST_DURATIONS",
    ),
    trace_files=("serve/app.py", "serve/asgi.py", "orchestrate/cova.py"),
    poll_routes=("/profile", "/health", "/readiness", "/health/ready",
                 "/metrics", "/stats", "/kv/blocks", "/kv/digests",
                 "/fleet", "/trace/{trace_id}"),
    race=RaceSpec(
        # serve.app's closure lock guarding the in-flight counters (the
        # dict_guards entry above names the same lock for the write rule)
        module_locks={"serve/app.py": {"inflight_lock":
                                       "app.inflight_lock"}},
        # the locks every thread in the process eventually serializes
        # behind: the engine-loop/serve futures seam, the QoS ledger (on
        # every admission AND completion), the step telemetry + flight
        # ring (written per step / per request, scraped concurrently),
        # the host KV pool (engine probes vs worker publishes), and the
        # request-path in-flight counters. Blocking while holding any of
        # these stalls request threads fleet-wide, not just one caller.
        hot_locks=(
            "EngineLoop._futures_lock",
            "TenantLedger._lock",
            "StepTelemetry._lock",
            "FlightRecorder._lock",
            "HostKVTier._lock",
            "AdmissionGate._lock",
            "DrainController._lock",
            "app.inflight_lock",
            # the kvnet transport: stats count on every handoff fetch and
            # every /kv/blocks serve; the client lock fronts every lane
            # thread's fetch — an HTTP call under either would serialize
            # the whole decode tier behind one slow peer
            "KvNetStats._lock",
            "KvNetClient._lock",
            # live migration: stats count on every ship/accept/resume and
            # the inbox fronts every replay — an HTTP ship under either
            # would serialize the whole drain behind one slow peer
            "MigrateStats._lock",
            "MigrationInbox._lock",
            # KV fabric: the probe rung runs ON the engine loop thread
            # and the directory serves every routing decision — an HTTP
            # probe or digest refresh under any of these would stall
            # admission fleet-wide behind one slow holder
            "KvFabricStats._lock",
            "KvDirectory._lock",
            "FabricProbe._lock",
            # the autoscaler: stats count on every tick and pool state
            # fronts every decision — a drain HTTP call under either
            # would freeze the control loop behind one slow pod
            "ScalerStats._lock",
            "Scaler._lock",
            # request reliability: the idempotency cache fronts every
            # keyed request (joiners wait on entry events OUTSIDE the
            # lock), and the hedge/budget/poison locks sit on cova's
            # dispatch hot path — an httpx call under any of them would
            # serialize the fan-out behind one slow pod
            "IdempotencyCache._lock",
            "RetryBudget._lock",
            "HedgeGovernor._lock",
            "PoisonRegistry._lock",
            "HedgeStats._lock",
        ),
        # The declared partial order is EMPTY on purpose: the control
        # plane's design rule is "no lock nesting at all" — every
        # declared lock protects a leaf structure and is released before
        # any call that could take another. Any observed cross-lock
        # acquisition (lexical or through the 2-level call graph) is
        # therefore a finding until a pair is deliberately added here.
        lock_order=(),
    ),
    ir=IrSpec(
        # every registered executable-factory variant the engine serves
        # with, built at tiny geometry by analysis/ir/factories.py:
        # runner.py's prefill/cont/decode (both feedback disciplines)/
        # verify/cross writers, the AOT export tier (core/aot.py's
        # artifact analog of per-rank NEFFs), and the SP legs in
        # parallel/ring.py. @tpN/@spN suffixes lower on an N-way virtual
        # CPU mesh; @tp2_paged lowers the Pallas paged path for the tpu
        # platform (trace + SPMD partition only, like the dryrun legs).
        programs=(
            "prefill", "prefill@tp2", "prefill_cont",
            "decode", "decode_feedback",
            "decode@tp2", "decode_feedback@tp2", "decode@tp2_paged",
            # ragged paged attention (SHAI_RAGGED_ATTENTION): full-window
            # decode + dynamic-start continuation, CPU gather legs and the
            # tpu-lowered Pallas kernel leg
            "decode_ragged", "decode_ragged@tp2",
            "prefill_rcont", "prefill_rcont@tp2",
            # fused mixed-phase step (SHAI_FUSED_STEP): decode rows + one
            # continuation-chunk window per dispatch, both async
            # disciplines on CPU and the tpu-lowered mixed-phase Pallas
            # leg — donation (pool; pos in feedback) and dtype drift gate
            # the fused path from day one
            "fused_step", "fused_step_feedback", "fused_step@tp2",
            # int8 KV pool (SHAI_KV_QUANT): quantized scatter on prefill,
            # requantizing decode write + in-executable dequant, and the
            # scale-carrying tier restore — dtype-drift and donation gate
            # these from day one
            "prefill_kvquant", "decode_kvquant", "tier_restore_quant",
            "verify",
            "cross_kv", "cross_slot_write",
            "tier_restore",
            "aot_decode_export",
            "ring@sp2", "ring_causal@sp2", "ulysses@sp2",
        ),
        # the engine's token paths are declared-bf16 compute (residual
        # stream, KV pool); f32 is legal only behind an explicit astype
        # (rmsnorm/logits islands). The SP legs are dtype-polymorphic
        # test rigs, not declared-bf16.
        bf16_programs=(
            "prefill", "prefill@tp2", "prefill_cont",
            "decode", "decode_feedback",
            "decode@tp2", "decode_feedback@tp2", "decode@tp2_paged",
            "decode_ragged", "decode_ragged@tp2",
            "prefill_rcont", "prefill_rcont@tp2",
            "fused_step", "fused_step_feedback", "fused_step@tp2",
            "prefill_kvquant", "decode_kvquant", "tier_restore_quant",
            "verify", "cross_kv", "cross_slot_write",
            "tier_restore",
        ),
        # a host callback inside any of these serializes every engine
        # step (decode) or admission (prefill/cross) on the host
        hot_programs=(
            "prefill", "prefill@tp2", "prefill_cont",
            "decode", "decode_feedback",
            "decode@tp2", "decode_feedback@tp2", "decode@tp2_paged",
            "decode_ragged", "decode_ragged@tp2",
            "prefill_rcont", "prefill_rcont@tp2",
            "fused_step", "fused_step_feedback", "fused_step@tp2",
            "prefill_kvquant", "decode_kvquant", "tier_restore_quant",
            "verify", "cross_kv", "cross_slot_write",
            "tier_restore",
        ),
        compositions={
            # one multihost slice may roll SHAI_ASYNC_DECODE across its
            # hosts: the two decode disciplines must keep identical
            # collective schedules or the first mixed step deadlocks
            "decode-disciplines@tp2": ("decode@tp2",
                                       "decode_feedback@tp2"),
            # the causal flag must not change ring attention's
            # communication pattern (a causal "optimization" that skips
            # rotations per-rank is exactly how ring impls deadlock)
            "ring-mask-variants@sp2": ("ring@sp2", "ring_causal@sp2"),
        },
        const_limit_bytes=1 << 16,
    ),
)
