"""Host-sync detector: device→host synchronization in declared hot paths.

The async decode loop's whole value is that the host never makes the
device wait (PR 6: 1.42x at bs=4). One stray ``np.asarray`` / ``.item()``
/ ``jax.device_get`` in the steady path re-serializes every step — and
nothing fails: tokens are still exact, only the step gap quietly grows.
This checker makes that a lint failure instead of a perf regression
someone has to notice on a dashboard.

Flagged inside hot-path functions (``contract.hot_paths``):

- ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` calls
- ``np.asarray(...)`` / ``np.array(...)`` (host pull of a device value;
  ``np.zeros``/``np.arange`` etc. are host allocations and stay legal)
- ``jax.device_get(...)``
- ``float(x)`` / ``int(x)`` on a non-literal (implicit device fetch when
  ``x`` is a traced/device value; ``int(len(...))`` and constants pass)

Intentional syncs carry the allow grammar with a reason::

    # shai-lint: allow(host-sync) the one blocking fetch of the pipeline
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .core import Finding, Module, resolved_dotted, snippet_of

RULE = "host-sync"

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_FUNCS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_CAST_FUNCS = {"int", "float"}


def _sync_kind(module: Module, node: ast.Call) -> Optional[str]:
    """Why this call is a host sync, or None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
        return f".{f.attr}()"
    d = resolved_dotted(module, f)
    if d in _SYNC_FUNCS:
        return f"{d}(...)"
    if isinstance(f, ast.Name) and f.id in _CAST_FUNCS and node.args:
        a = node.args[0]
        if isinstance(a, ast.Constant):
            return None
        if isinstance(a, ast.Call) and isinstance(a.func, ast.Name) \
                and a.func.id == "len":
            return None
        return f"{f.id}(...) on a non-literal"
    return None


def _lambda_targets(stmt: ast.AST) -> List[Tuple[str, ast.Lambda]]:
    """(name, lambda node) for ``name = lambda ...`` assignments — a
    callable bound this way is a function in every sense the hot-path
    contract cares about, so it inherits hot scope exactly like a def."""
    if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        return []
    value = getattr(stmt, "value", None)
    if not isinstance(value, ast.Lambda):
        return []
    targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
    out = []
    for t in targets:
        name = t.id if isinstance(t, ast.Name) else (
            t.attr if isinstance(t, ast.Attribute) else None)
        if name:
            out.append((name, value))
    return out


def _qualname_defs(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(qualname, body node) for every function — ``def``, ``async def``,
    and assigned ``lambda`` alike — ``Class.method`` style."""
    out: List[Tuple[str, ast.AST]] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                for name, lam in _lambda_targets(child):
                    out.append((f"{prefix}{name}", lam))
                walk(child, prefix)

    walk(tree, "")
    return out


def check(modules: List[Module], contract) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        hot = contract.hot_paths.get(module.relpath)
        if not hot:
            continue
        star = "*" in hot
        seen = set()  # a nested def is walked under its parent too
        for qual, fn in _qualname_defs(module.tree):
            # a nested def inherits its enclosing hot scope; the qualname
            # prefix check covers both the function and its inner defs
            if not star and not any(
                    qual == h or qual.startswith(h + ".") for h in hot):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                kind = _sync_kind(module, node)
                if kind is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                allowed, reason, problem = module.allow_at(node, RULE)
                msg = f"host sync {kind} in declared hot path"
                if problem:
                    msg += f" ({problem})"
                findings.append(Finding(
                    rule=RULE, path=module.relpath, line=node.lineno,
                    context=qual, message=msg, allowed=allowed,
                    reason=reason, snippet=snippet_of(module, node)))
    return findings
