"""shai-lint: repo-specific AST invariant checkers (stdlib-only).

The serving stack survives on invariants no test exercises directly: the
async decode steady path must never block on the host, donated buffers must
never be read after dispatch, engine state has a declared threading
contract, every env knob parses leniently and is documented, and every
debug/poll route stays out of the flight-recorder's trace ring. Each of
these bug classes was found LIVE during review hardening; this package
makes them mechanical.

- ``core``      shared infra: findings, module loading, the inline
                allowlist grammar, baseline IO, the all-checkers runner
- ``contract``  THE declared tables every checker reads: hot-path
                functions, donation bindings, the thread-discipline
                contract, env parse/doc exemptions, poll routes
- ``hostsync``  device→host synchronization inside declared hot paths
- ``donation``  reads of donated buffers after the donating dispatch
- ``threads``   attribute-write sites vs the concurrency contract
- ``envknobs``  env reads must use the lenient parsers + appear in README
                (+ deploy manifests may only set knobs the code reads)
- ``routes``    GET debug/poll routes must be in ``trace_exclude``
- ``race``      shai-race: lock-order inversions (acquisition graph +
                2-level call propagation), unbounded blocking calls
                under declared hot locks, and unguarded READS of
                lock-guarded state — a separate pass
                (``shai_lint.py --race``) with its own baseline rules
- ``ir/``       jaxpr-lint: IR-level checks on the COMPILED executable
                factories (donation efficacy, dtype drift, collective
                schedules, host interop, baked constants) — NOT imported
                here; it needs jax and runs via ``shai_lint.py --ir``

CLI: ``python scripts/shai_lint.py`` (JSON + human output, committed
findings baseline with rename-stable fingerprints); ``--ir`` for the IR
pass; ``scripts/check_all.py`` for the one-exit-code repo gate. Tier-1:
``tests/test_static_analysis.py`` + ``tests/test_ir_analysis.py``.

Layering: this package (``ir/`` excepted) imports nothing from the rest
of the repo and no third-party deps — the AST linter must load in
milliseconds and never depend on the code it inspects.
"""

from .core import (  # noqa: F401
    Finding,
    Module,
    iter_modules,
    load_baseline,
    run_all,
    save_baseline,
)
from .contract import DEFAULT_CONTRACT, Contract  # noqa: F401
from .race import RACE_RULES, run_race  # noqa: F401
