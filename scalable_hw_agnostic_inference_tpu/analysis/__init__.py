"""shai-lint: repo-specific AST invariant checkers (stdlib-only).

The serving stack survives on invariants no test exercises directly: the
async decode steady path must never block on the host, donated buffers must
never be read after dispatch, engine state has a declared threading
contract, every env knob parses leniently and is documented, and every
debug/poll route stays out of the flight-recorder's trace ring. Each of
these bug classes was found LIVE during review hardening; this package
makes them mechanical.

- ``core``      shared infra: findings, module loading, the inline
                allowlist grammar, baseline IO, the all-checkers runner
- ``contract``  THE declared tables every checker reads: hot-path
                functions, donation bindings, the thread-discipline
                contract, env parse/doc exemptions, poll routes
- ``hostsync``  device→host synchronization inside declared hot paths
- ``donation``  reads of donated buffers after the donating dispatch
- ``threads``   attribute-write sites vs the concurrency contract
- ``envknobs``  env reads must use the lenient parsers + appear in README
- ``routes``    GET debug/poll routes must be in ``trace_exclude``

CLI: ``python scripts/shai_lint.py`` (JSON + human output, committed
findings baseline). Tier-1: ``tests/test_static_analysis.py``.

Layering: imports nothing from the rest of the package and no third-party
deps — the linter must load in milliseconds and never depend on the code
it inspects.
"""

from .core import (  # noqa: F401
    Finding,
    Module,
    iter_modules,
    load_baseline,
    run_all,
    save_baseline,
)
from .contract import DEFAULT_CONTRACT, Contract  # noqa: F401
