"""Thread-discipline checker: attribute-write sites vs the declared
concurrency contract (``contract.thread_contract``).

The engine is mutated from exactly one thread (the engine loop); the serve
lane reaches it only through ``EngineLoop``'s queues, whose futures table
is the one lock-guarded cross-thread structure. None of that is visible
in types — a well-meaning ``service.loop.engine.waiting.append(...)`` from
a request handler compiles, passes every test that doesn't race, and
corrupts batch state under load. This checker turns the contract into
failures at the write site.

Per :class:`~.contract.ClassPolicy`:

- ``immutable_after_init`` attrs: ``self.X`` writes (assign/augassign/
  subscript-store/mutator call) only inside ``init_methods``.
- ``lock_guarded`` attrs: every write site lexically inside
  ``with self.<lock>:``  (init methods exempt — the object is not yet
  shared).
- everything else is owner-thread-only: writes through a declared
  instance marker (``engine.``, ``.loop.`` …) are legal only in
  ``owning_modules``.

``contract.dict_guards`` covers closure-state dicts (serve.app's
``state``): writes to the guarded keys must hold the named lock.

Deliberate exceptions carry ``# shai-lint: allow(thread) <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, dotted, snippet_of

RULE = "thread"

#: method names that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "clear", "update", "remove", "discard", "add",
    "setdefault", "sort", "reverse",
}


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_shai_parent", None)
    return None


def _holds_lock(node: ast.AST, lock_paths: Set[str]) -> bool:
    """True when ``node`` sits lexically inside ``with <lock>:`` for one
    of the dotted lock paths."""
    cur = getattr(node, "_shai_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                if dotted(item.context_expr) in lock_paths:
                    return True
        cur = getattr(cur, "_shai_parent", None)
    return False


def _class_callables(cls: ast.ClassDef):
    """(name, body node) for every callable in the class body: ``def``,
    ``async def``, and ``name = lambda ...`` attributes — a mutator call
    inside a class-level lambda is a write site like any other."""
    for n in cls.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n.name, n
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Lambda):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    yield t.id, n.value
        elif isinstance(n, ast.AnnAssign) \
                and isinstance(n.value, ast.Lambda) \
                and isinstance(n.target, ast.Name):
            yield n.target.id, n.value


def _self_write_sites(cls: ast.ClassDef):
    """Yield (method name, attr, site node, kind) for every write through
    ``self`` in the class body: plain/aug assigns to ``self.X``, subscript
    stores into ``self.X[...]``, and mutator calls ``self.X.m(...)``."""
    for method_name, method in _class_callables(cls):
        for node in ast.walk(method):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Attribute) \
                            and isinstance(leaf.value, ast.Name) \
                            and leaf.value.id == "self" \
                            and isinstance(leaf.ctx, ast.Store):
                        yield method_name, leaf.attr, node, "write"
                    elif isinstance(leaf, ast.Subscript) \
                            and isinstance(leaf.ctx, ast.Store):
                        base = leaf.value
                        if isinstance(base, ast.Attribute) \
                                and isinstance(base.value, ast.Name) \
                                and base.value.id == "self":
                            yield method_name, base.attr, node, "item write"
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                recv = node.func.value
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self":
                    yield method_name, recv.attr, node, f".{node.func.attr}()"


def _finding(module: Module, node: ast.AST, context: str, message: str
             ) -> Finding:
    allowed, reason, problem = module.allow_at(node, RULE)
    if problem:
        message += f" ({problem})"
    return Finding(rule=RULE, path=module.relpath, line=node.lineno,
                   context=context, message=message, allowed=allowed,
                   reason=reason, snippet=snippet_of(module, node))


def _check_class_body(module: Module, cls: ast.ClassDef, policy,
                      findings: List[Finding]) -> None:
    lock_attrs = set(policy.lock_guarded)
    for method_name, attr, node, kind in _self_write_sites(cls):
        in_init = method_name in policy.init_methods
        if attr in policy.immutable_after_init and not in_init \
                and not kind.startswith("."):
            # mutator CALLS (`self.cache.extend(...)`) are the attr's own
            # object managing itself — immutability here is about the
            # BINDING (and direct item stores into it) staying fixed
            findings.append(_finding(
                module, node, f"{cls.name}.{method_name}",
                f"{kind} to immutable-after-init attr `{attr}` outside "
                f"{'/'.join(policy.init_methods)}"))
        elif attr in lock_attrs and not in_init:
            lock = policy.lock_guarded[attr]
            if not _holds_lock(node, {f"self.{lock}", lock}):
                findings.append(_finding(
                    module, node, f"{cls.name}.{method_name}",
                    f"{kind} to lock-guarded attr `{attr}` outside "
                    f"`with self.{lock}`"))


def _external_write_paths(module: Module):
    """(site node, dotted path, kind) for attribute writes and mutator
    calls anywhere in the module (coarse: callers filter by markers)."""
    for node in ast.walk(module.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Attribute) \
                        and isinstance(getattr(leaf, "ctx", None), ast.Store):
                    d = dotted(leaf)
                    if d is not None:
                        yield node, d, "write"
                elif isinstance(leaf, ast.Subscript) \
                        and isinstance(getattr(leaf, "ctx", None), ast.Store):
                    d = dotted(leaf.value)
                    if d is not None:
                        yield node, d, "item write"
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            d = dotted(node.func.value)
            if d is not None:
                yield node, d, f".{node.func.attr}()"


def _matches_marker(path: str, markers: Tuple[str, ...]) -> bool:
    """A write path hits an instance marker as a leading segment
    (``engine.slots`` for marker ``engine.``) or an infix (``service.loop.
    engine.slots`` for ``.engine.``)."""
    probe = f".{path}"
    return any(m.lstrip(".") and
               (probe.find(f".{m.lstrip('.')}") == 0
                or (m.startswith(".") and m in probe))
               for m in markers)


def check(modules: List[Module], contract) -> List[Finding]:
    findings: List[Finding] = []
    policies = contract.thread_contract
    for module in modules:
        # 1) in-class writes vs immutability + lock requirements
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and node.name in policies:
                _check_class_body(module, node, policies[node.name],
                                  findings)
        # 2) writes through instance markers from non-owning modules
        for cls_name, policy in policies.items():
            if not policy.instance_markers or not policy.owning_modules:
                continue
            if module.relpath in policy.owning_modules:
                continue
            for site, path, kind in _external_write_paths(module):
                if not _matches_marker(path, policy.instance_markers):
                    continue
                # writes from inside the class's own body were checked above
                fn = _enclosing_function(site)
                findings.append(_finding(
                    module, site,
                    getattr(fn, "name", "<module>"),
                    f"{kind} to `{path}` — {cls_name} state is "
                    f"owner-thread-only (owning modules: "
                    f"{', '.join(policy.owning_modules)})"))
        # 3) guarded closure dicts
        guards = contract.dict_guards.get(module.relpath, {})
        if guards:
            for node in ast.walk(module.tree):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if not (isinstance(leaf, ast.Subscript)
                                and isinstance(getattr(leaf, "ctx", None),
                                               ast.Store)
                                and isinstance(leaf.value, ast.Name)
                                and leaf.value.id in guards):
                            continue
                        keys, lock = guards[leaf.value.id]
                        key = leaf.slice
                        if isinstance(key, ast.Constant) \
                                and key.value in keys \
                                and not _holds_lock(leaf, {lock}):
                            fn = _enclosing_function(leaf)
                            findings.append(_finding(
                                module, node,
                                getattr(fn, "name", "<module>"),
                                f"write to `{leaf.value.id}[\"{key.value}\"]`"
                                f" outside `with {lock}`"))
    return findings
