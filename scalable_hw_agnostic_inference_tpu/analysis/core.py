"""shai-lint shared infrastructure: findings, parsed modules, the inline
allowlist grammar, the findings baseline, and the all-checkers runner.

Allowlist grammar (one line, same line as the finding or the line above)::

    # shai-lint: allow(<rule>[,<rule>...]) <reason>

The reason is REQUIRED: an allow comment documents an intentional
violation, it does not silence one. A reason-less allow comment leaves the
finding live and adds a note saying why — the reviewer sees both.

Baseline: a committed JSON list of finding fingerprints
(``analysis/baseline.json``). Fingerprints are line-number-free so code
motion above a pre-existing finding doesn't churn the file. CI semantics:
a finding in the baseline is known debt (reported, exit 0); a finding not
in the baseline fails the run (exit 1). ``scripts/shai_lint.py
--update-baseline`` rewrites the file from a fresh run.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

#: repo root (the directory holding the package and README.md)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG_ROOT = os.path.join(REPO_ROOT, "scalable_hw_agnostic_inference_tpu")
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
README_PATH = os.path.join(REPO_ROOT, "README.md")
DEPLOY_ROOT = os.path.join(REPO_ROOT, "deploy")

_ALLOW_RE = re.compile(
    r"#\s*shai-lint:\s*allow\(([a-zA-Z0-9_\-, ]+)\)\s*(.*)$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker hit. ``context`` is a stable anchor (qualname, env var
    name, route pattern); ``message`` must be line-number-free so the
    baseline fingerprint survives code motion. ``snippet`` is the
    whitespace-normalized source of the offending node's first line —
    fingerprints are built from (rule, context, message, snippet), never
    from ``path`` or ``line``, so moving a file (or the code within it)
    does not resurrect every baselined finding under new fingerprints."""

    rule: str
    path: str           # repo-relative, forward slashes (display only)
    line: int
    context: str
    message: str
    allowed: bool = False   # suppressed by a valid inline allow comment
    reason: str = ""        # the allow comment's reason when allowed
    snippet: str = ""       # normalized source anchor (display + identity)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.context}|{self.message}|{self.snippet}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (allowed)" if self.allowed else ""
        return (f"{self.path}:{self.line} [{self.rule}] {self.context}: "
                f"{self.message}{tag}")


class Module:
    """One parsed source file: AST with parent links, source lines,
    module-level string constants, and import aliases."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._shai_parent = node  # type: ignore[attr-defined]
        #: module-level NAME = "literal" (env-name constants like ENV_TTFT_MS)
        self.str_constants: Dict[str, str] = {}
        #: import alias -> dotted module ("np" -> "numpy")
        self.aliases: Dict[str, str] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.str_constants[node.targets[0].id] = node.value.value
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    # -- allowlist grammar -------------------------------------------------

    def allow_at(self, node: ast.AST, rule: str
                 ) -> Tuple[bool, str, Optional[str]]:
        """(allowed, reason, problem) for ``node`` under ``rule``: an allow
        comment on the node's first or last line, or anywhere in the
        contiguous comment block directly above it. ``problem`` is set
        when a matching comment exists but is malformed (missing reason)
        — the finding stays live."""
        lineno = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", lineno) or lineno
        candidates = [lineno, end]
        ln = lineno - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].lstrip().startswith("#"):
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            if not 1 <= ln <= len(self.lines):
                continue
            m = _ALLOW_RE.search(self.lines[ln - 1])
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",")}
            if rule not in rules:
                continue
            reason = m.group(2).strip()
            if not reason:
                return (False, "",
                        "allow comment is missing its required reason")
            return True, reason, None
        return False, "", None


def snippet_of(module: Module, node: ast.AST) -> str:
    """Whitespace-normalized source of ``node``'s first line — the
    path-free half of a finding's identity (the other half is the
    qualified ``context``)."""
    lineno = getattr(node, "lineno", 0)
    if not 1 <= lineno <= len(module.lines):
        return ""
    return " ".join(module.lines[lineno - 1].split())


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolved_dotted(module: Module, node: ast.AST) -> Optional[str]:
    """Like :func:`dotted` but with the first segment resolved through the
    module's import aliases (``np.asarray`` -> ``numpy.asarray``)."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    base = module.aliases.get(head, head)
    return f"{base}.{rest}" if rest else base


def str_arg(module: Module, node: ast.AST) -> Optional[str]:
    """Resolve an expression to a string: literal, or a module-level
    string constant by name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return module.str_constants.get(node.id)
    return None


def iter_modules(pkg_root: str = PKG_ROOT) -> List[Module]:
    """Every parseable ``*.py`` under the package tree, sorted by relpath
    (relative to the REPO root, e.g. ``scalable_hw_agnostic_inference_tpu/
    engine/engine.py`` shortens to ``engine/engine.py``)."""
    mods: List[Module] = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, pkg_root)
            with open(full, encoding="utf-8") as f:
                src = f.read()
            mods.append(Module(rel, src))
    return mods


def deploy_env_names(deploy_root: str = DEPLOY_ROOT
                     ) -> Dict[str, Tuple[str, int]]:
    """``SHAI_*`` names set in K8s manifests (and the generator that
    renders them): name -> first (repo-relative path, line). A name here
    that no code reads is a typo'd knob silently no-oping in YAML."""
    import re as _re

    pat = _re.compile(r"\bSHAI_[A-Z0-9_]+\b")
    out: Dict[str, Tuple[str, int]] = {}
    if not os.path.isdir(deploy_root):
        return out
    for dirpath, dirnames, filenames in os.walk(deploy_root):
        dirnames[:] = sorted(dirnames)
        for fn in sorted(filenames):
            if not fn.endswith((".yaml", ".yml", ".py")):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, REPO_ROOT).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                for i, ln in enumerate(f, 1):
                    for m in pat.finditer(ln):
                        out.setdefault(m.group(0), (rel, i))
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH) -> List[str]:
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return []
    return list(data.get("findings", []))


def save_baseline(findings: Iterable[Finding],
                  path: str = BASELINE_PATH,
                  carry: Iterable[str] = ()) -> None:
    # version 2: rename-stable fingerprints (rule|context|message|snippet,
    # no path segment). Version-1 entries still load — they simply never
    # match a fresh finding, so they surface as stale and the file shrinks
    # through the normal --update-baseline workflow. ``carry`` preserves
    # fingerprints owned by a pass that did not run (the CLI rewrites one
    # pass's rules at a time).
    fps = sorted({f.fingerprint for f in findings if not f.allowed}
                 | set(carry))
    with open(path, "w") as f:
        json.dump({"version": 2, "findings": fps}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


# -- runner ------------------------------------------------------------------

def run_all(modules: Optional[List[Module]] = None, contract=None,
            readme_text: Optional[str] = None,
            deploy_names: Optional[Dict[str, Tuple[str, int]]] = None
            ) -> List[Finding]:
    """Run every AST checker; returns ALL findings (allowed ones included,
    flagged) sorted by (path, line, rule). Callers filter on ``allowed``.
    The IR pass (``analysis/ir``) is separate — it imports jax and is run
    explicitly via ``scripts/shai_lint.py --ir``."""
    from . import donation, envknobs, hostsync, routes, threads
    from .contract import DEFAULT_CONTRACT

    contract = contract or DEFAULT_CONTRACT
    if modules is None:
        modules = iter_modules()
    if readme_text is None:
        try:
            with open(README_PATH, encoding="utf-8") as f:
                readme_text = f.read()
        except OSError:
            readme_text = ""
    if deploy_names is None:
        deploy_names = deploy_env_names()
    findings: List[Finding] = []
    findings += hostsync.check(modules, contract)
    findings += donation.check(modules, contract)
    findings += threads.check(modules, contract)
    findings += envknobs.check(modules, contract, readme_text,
                               deploy_names=deploy_names)
    findings += routes.check(modules, contract)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
