"""Donation-after-use checker: reads of donated buffers after dispatch.

``jax.jit(..., donate_argnums=...)`` hands the argument's device buffer to
the executable — after the call the caller's array aliases freed (or
reused) memory. Reading it is not an error JAX reliably reports: on TPU it
can read garbage (silent KV corruption, invisible to the differential
oracle because BOTH disciplines would read the same garbage). The engine's
idiom is donate-and-rebind in one statement (``self.cache.kv, ... =
decode(*args)``); this checker flags every deviation.

Mechanics (per ``contract``):

1. **Factory registry** — scan ``donation_factory_files`` for
   ``jax.jit(fn, donate_argnums=...)`` inside ``def make_X``; the registry
   maps factory name -> union of donated positions (a conditional
   ``(1, 3) if feedback else (1,)`` contributes both).
2. **Binding resolution** — inside each function of
   ``donation_check_files``, a name becomes a *donating callable* via a
   direct ``jax.jit`` assignment, a factory call, a declared accessor
   (``_, decode = self._decode_for(...)``), a declared factory-built
   instance attribute (``self._cross_write``), or a declared parameter.
3. **Call-site tracking** — at each donating call, the argument at every
   donated position (resolved through literal ``*args`` lists built with
   ``args = [...]`` / ``args += [...]`` / ``args.append(...)``) starts a
   watch on its dotted path. A later READ of that path in the same
   function is a finding; a STORE to it (including the donating
   statement's own assignment targets) retires the watch.
4. ``donating_calls`` declares helper methods that donate specific
   positional arguments onward (the async dispatch helper).

Statement order is source order — control flow is not modeled; this is a
lint for a codebase whose convention is strictly linear donate-and-rebind.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .core import Finding, Module, dotted, resolved_dotted, snippet_of

RULE = "donation"


# -- factory registry --------------------------------------------------------

def _donate_positions(fn_scope: ast.AST, value: ast.AST) -> Set[int]:
    """Int positions named by a ``donate_argnums`` value expression:
    literal int/tuple, a conditional of literals, or a local name assigned
    one of those earlier in ``fn_scope``."""
    out: Set[int] = set()

    def collect(node: Optional[ast.AST]) -> None:
        if node is None:
            return
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            out.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                collect(e)
        elif isinstance(node, ast.IfExp):
            collect(node.body)
            collect(node.orelse)
        elif isinstance(node, ast.Name):
            for stmt in ast.walk(fn_scope):
                if isinstance(stmt, ast.Assign) \
                        and any(isinstance(t, ast.Name) and t.id == node.id
                                for t in stmt.targets):
                    collect(stmt.value)

    collect(value)
    return out


def factory_registry(modules: List[Module], contract
                     ) -> Dict[str, FrozenSet[int]]:
    """factory def name -> union of donated positions it compiles with."""
    reg: Dict[str, Set[int]] = {}
    for module in modules:
        if module.relpath not in contract.donation_factory_files:
            continue
        for top in module.tree.body:
            if not isinstance(top, ast.FunctionDef):
                continue
            for node in ast.walk(top):
                if not (isinstance(node, ast.Call)
                        and resolved_dotted(module, node.func) == "jax.jit"):
                    continue
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        pos = _donate_positions(top, kw.value)
                        if pos:
                            reg.setdefault(top.name, set()).update(pos)
    return {k: frozenset(v) for k, v in reg.items()}


# -- per-function tracking ---------------------------------------------------

def _statements(body: List[ast.stmt]) -> List[ast.stmt]:
    """Flatten a function body into source-ordered simple statements
    (descending into if/for/while/with/try bodies, NOT into nested defs)."""
    out: List[ast.stmt] = []
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        out.append(stmt)
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, field, None)
            if isinstance(inner, list):
                out.extend(_statements(inner))
        for h in getattr(stmt, "handlers", []) or []:
            out.extend(_statements(h.body))
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out


def _shallow(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a compound statement evaluates ITSELF (test, iter,
    with-items) — its body statements are visited in their own right, so
    scanning the whole subtree here would double-visit and mis-order."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _store_paths(target: ast.AST) -> Set[str]:
    """Dotted paths a Store target rebinds (tuple targets unpacked;
    subscript stores rebind nothing)."""
    out: Set[str] = set()
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            out |= _store_paths(e)
    else:
        d = dotted(target)
        if d is not None:
            out.add(d)
    return out


def _jit_donations(module: Module, call: ast.Call,
                   scope: ast.AST) -> Optional[FrozenSet[int]]:
    if resolved_dotted(module, call.func) != "jax.jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            pos = _donate_positions(scope, kw.value)
            if pos:
                return frozenset(pos)
    return None


class _Scope:
    """Linear donation tracking through one function body."""

    def __init__(self, module: Module, qual: str, fn: ast.AST,
                 registry: Dict[str, FrozenSet[int]], contract):
        self.module = module
        self.qual = qual
        self.fn = fn
        self.registry = registry
        self.contract = contract
        self.bindings: Dict[str, FrozenSet[int]] = {}
        self.list_vars: Dict[str, List[ast.expr]] = {}
        #: watched dotted path -> (donating callee, donation line)
        self.watch: Dict[str, Tuple[str, int]] = {}
        self.findings: List[Finding] = []
        params = contract.param_factories.get(qual, {})
        for pname, factory in params.items():
            if factory in registry:
                self.bindings[pname] = registry[factory]

    # binding helpers ------------------------------------------------------

    def _bind_from_value(self, targets: List[ast.AST],
                         value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        donated = _jit_donations(self.module, value, self.fn)
        callee = dotted(value.func)
        factory = None
        result_index: Optional[int] = None
        if donated is None and callee is not None:
            tail = callee.split(".")[-1]
            if tail in self.registry:
                factory, result_index = tail, None
            elif tail in self.contract.accessor_factories:
                factory, result_index = self.contract.accessor_factories[tail]
            if factory is not None:
                donated = self.registry.get(factory)
        if donated is None:
            return
        for target in targets:
            if result_index is not None and isinstance(
                    target, (ast.Tuple, ast.List)):
                if result_index < len(target.elts) and isinstance(
                        target.elts[result_index], ast.Name):
                    self.bindings[target.elts[result_index].id] = donated
            elif isinstance(target, ast.Name):
                self.bindings[target.id] = donated

    def _track_list(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.List):
            self.list_vars[stmt.targets[0].id] = list(stmt.value.elts)
        elif isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, ast.Name) \
                and isinstance(stmt.op, ast.Add) \
                and stmt.target.id in self.list_vars \
                and isinstance(stmt.value, ast.List):
            self.list_vars[stmt.target.id].extend(stmt.value.elts)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            c = stmt.value
            if isinstance(c.func, ast.Attribute) \
                    and isinstance(c.func.value, ast.Name) \
                    and c.func.value.id in self.list_vars:
                if c.func.attr == "append" and c.args:
                    self.list_vars[c.func.value.id].append(c.args[0])
                elif c.func.attr == "extend" and c.args \
                        and isinstance(c.args[0], ast.List):
                    self.list_vars[c.func.value.id].extend(c.args[0].elts)

    # call-site donation ---------------------------------------------------

    def _donations_of_call(self, call: ast.Call
                           ) -> Optional[Tuple[str, FrozenSet[int]]]:
        callee = dotted(call.func)
        if callee is None:
            return None
        tail = callee.split(".")[-1]
        if isinstance(call.func, ast.Name) \
                and call.func.id in self.bindings:
            return callee, self.bindings[call.func.id]
        if tail in self.contract.attr_factories:
            donated = self.registry.get(self.contract.attr_factories[tail])
            if donated:
                return callee, donated
        if tail in self.contract.donating_calls:
            return callee, frozenset(self.contract.donating_calls[tail])
        return None

    def _positional_args(self, call: ast.Call) -> List[ast.expr]:
        if len(call.args) == 1 and isinstance(call.args[0], ast.Starred) \
                and isinstance(call.args[0].value, ast.Name):
            return list(self.list_vars.get(call.args[0].value.id, []))
        return [a for a in call.args if not isinstance(a, ast.Starred)]

    def _register_donations(self, stmt: ast.stmt) -> None:
        for node in (n for root in _shallow(stmt)
                     for n in ast.walk(root)):
            if not isinstance(node, ast.Call):
                continue
            got = self._donations_of_call(node)
            if got is None:
                continue
            callee, positions = got
            args = self._positional_args(node)
            for i in sorted(positions):
                if i >= len(args):
                    continue
                path = dotted(args[i])
                if path is not None:
                    self.watch[path] = (callee, node.lineno)

    def _scan_reads(self, stmt: ast.stmt) -> None:
        if not self.watch:
            return
        for node in (n for root in _shallow(stmt)
                     for n in ast.walk(root)):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            parent = getattr(node, "_shai_parent", None)
            if isinstance(parent, ast.Attribute):
                continue  # only the full dotted chain matches
            path = dotted(node)
            if path is None:
                continue
            # a read of the donated path itself, or of anything under it
            # (`kv.shape` after `kv` was donated)
            watched = next((w for w in self.watch
                            if path == w or path.startswith(w + ".")),
                           None)
            if watched is None:
                continue
            callee, dline = self.watch[watched]
            path = watched
            allowed, reason, problem = self.module.allow_at(node, RULE)
            msg = (f"read of `{path}` after its buffer was donated to "
                   f"`{callee}(...)`")
            if problem:
                msg += f" ({problem})"
            self.findings.append(Finding(
                rule=RULE, path=self.module.relpath, line=node.lineno,
                context=self.qual, message=msg, allowed=allowed,
                reason=reason, snippet=snippet_of(self.module, node)))
            del self.watch[path]  # one finding per donated path

    def _kill_stores(self, stmt: ast.stmt) -> None:
        killed: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                killed |= _store_paths(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            killed |= _store_paths(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            killed |= _store_paths(stmt.target)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    killed |= _store_paths(item.optional_vars)
        for path in killed:
            self.watch.pop(path, None)

    def run(self) -> List[Finding]:
        for stmt in _statements(self.fn.body):
            # reads of previously-donated paths fire BEFORE this
            # statement's own donations/rebinds take effect
            self._scan_reads(stmt)
            if isinstance(stmt, ast.Assign):
                self._bind_from_value(stmt.targets, stmt.value)
            self._track_list(stmt)
            self._register_donations(stmt)
            self._kill_stores(stmt)
        return self.findings


def _walk_defs(tree: ast.Module):
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{prefix}{child.name}", child
                yield from walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def check(modules: List[Module], contract) -> List[Finding]:
    registry = factory_registry(modules, contract)
    findings: List[Finding] = []
    for module in modules:
        if module.relpath not in contract.donation_check_files:
            continue
        for qual, fn in _walk_defs(module.tree):
            findings += _Scope(module, qual, fn, registry, contract).run()
    return findings
