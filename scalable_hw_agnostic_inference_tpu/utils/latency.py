"""Latency percentile collection — dependency-free, shared by the serving
layer's request collector and the engine's TTFT/TPOT instruments (the engine
must not import the serve package: layering)."""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

PERCENTILES = (0, 50, 90, 95, 99, 100)


class LatencyCollector:
    """Thread-safe reservoir of request latencies with percentile readout.

    Once the reservoir is full, Vitter's Algorithm R keeps every sample in
    with probability ``max_samples / total`` — a uniform random subsample of
    the whole stream. (The previous ``total % max_samples`` overwrite was
    deterministic round-robin: it kept exactly the LAST ``max_samples``
    observations, so long-tail samples older than one reservoir length
    could never survive and percentiles silently became a sliding window.)
    The RNG is seeded (private stream) so runs are reproducible and the
    global ``random`` state is untouched.
    """

    def __init__(self, max_samples: int = 100_000,
                 seed: Optional[int] = 0x5EED):
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self._max_samples = max_samples
        self._total = 0
        self._rng = random.Random(seed)

    def record(self, latency_s: float) -> None:
        with self._lock:
            self._total += 1
            if len(self._samples) < self._max_samples:
                self._samples.append(latency_s)
            else:
                # Algorithm R: admit with p = max/total, evicting a uniform
                # victim — every observation ends up kept with equal chance
                j = self._rng.randrange(self._total)
                if j < self._max_samples:
                    self._samples[j] = latency_s

    def timed(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` and record its wall time; returns ``fn``'s result."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        self.record(time.perf_counter() - t0)
        return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    @staticmethod
    def _interp(data: List[float], p: float) -> float:
        if not data:
            return 0.0
        if p <= 0:
            return data[0]
        if p >= 100:
            return data[-1]
        # linear interpolation between closest ranks
        rank = (p / 100.0) * (len(data) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(data) - 1)
        frac = rank - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def percentile(self, p: float) -> float:
        with self._lock:
            data = sorted(self._samples)
        return self._interp(data, p)

    def report(self) -> Dict[str, float]:
        # one locked snapshot + one sort, so percentiles within a report are
        # mutually consistent under concurrent record()s
        with self._lock:
            data = sorted(self._samples)
        return {f"p{p}": self._interp(data, p) for p in PERCENTILES}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._total = 0


