from .env import ServeConfig, env_str, env_int, env_float  # noqa: F401
