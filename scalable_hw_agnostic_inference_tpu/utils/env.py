"""Environment-variable config contract for serving pods.

The reference uses bare ``os.environ[...]`` reads scattered through every
server (contract enumerated in SURVEY.md §2.2; e.g. reference
``app/run-sd.py:15-23``, ``app/flux_model_api.py:33-36``,
``app/run-llama.py:17``). Here the contract is one typed, validated dataclass
shared by every server, so a deployment YAML's ``env:`` block is the single
source of pod configuration exactly as in the reference — but with defaults,
types, and a ``describe()`` for the self-describing ``GET /`` endpoint.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


# one leniency owner: a malformed knob degrades to its default with a
# warning instead of crash-looping pod boot (PR 7's SHAI_HBM_WINDOW=8.5
# lesson, generalized by shai-lint's env-parse rule). Range/enum VALIDATION
# stays strict below — a value that parses but is out of contract
# (DEVICE=cuda) still fails loudly.
from ..obs.util import env_flag, env_float, env_int  # noqa: F401


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v


def env_bool(name: str, default: bool) -> bool:
    return env_flag(name, default)


VALID_DEVICES = ("tpu", "cpu")


@dataclasses.dataclass
class ServeConfig:
    """Uniform pod configuration, set from a Deployment's ``env:`` block.

    Field-for-field parity with the reference env contract (SURVEY.md §2.2),
    minus the CUDA-only knobs; ``device`` accepts ``tpu`` or ``cpu`` (the
    reference's ``xla|cuda|triton|cpu`` seam, with the TPU tier replacing the
    accelerator branches).
    """

    # identity / control-plane
    app: str = "model"
    nodepool: str = "local"
    pod_name: str = "local-pod"
    # model selection
    device: str = "tpu"
    model_id: str = ""
    compiled_model_id: str = ""          # artifact-store key for AOT artifacts
    hf_token: str = ""
    # task knobs
    num_inference_steps: int = 25        # diffusion denoise steps
    num_of_runs_inf: int = 2             # warmup/benchmark inference count
    max_new_tokens: int = 128
    max_seq_len: int = 512
    height: int = 512
    width: int = 512
    guidance_scale: float = 7.5
    batch_size: int = 1
    scheduler: str = "ddim"              # diffusion sampler: ddim | euler
    steps_buckets: str = ""              # extra allowed steps values, csv
    # weight-only quantization for causal-LM units: "" = bf16, "int8" =
    # per-channel int8 matmul kernels (ops.quant) — what lets an 8B distill
    # serve from ONE v5e chip (the engine units read the same knob from the
    # vllm_config ConfigMap instead; this env form covers LlamaService and
    # ConfigMap-less engine units)
    quantization: str = ""
    # diffusion request coalescing: concurrent /genimage requests sharing
    # (steps, guidance) batch into ONE denoise call, pow2 batch buckets up
    # to this cap (1 = off; each bucket costs one compiled executable)
    sd_batch_max: int = 1
    vllm_config: str = "/vllm_config.yaml"  # engine ConfigMap mount path
    # mesh / parallelism
    mesh_spec: str = ""                  # e.g. "tp=4" or "dp=2,tp=4"; "" = single device
    submesh: str = ""                    # e.g. "0:4" — device-slice placement
    # serving
    port: int = 8000
    warmup: bool = True
    metrics_port: int = 9100
    # resilience (serve-layer request lifecycle)
    deadline_ms: int = 0                 # default per-request deadline; 0 = none
    drain_budget_s: float = 30.0         # SIGTERM: max seconds to finish in-flight
    # admission-gate shed thresholds; defaults mirror the failover
    # controller's OverloadThresholds so pod-level 429s and fleet-level
    # failover describe the same saturation line
    admit_max_queue: float = 8.0
    admit_max_kv: float = 0.95
    max_inflight: int = 0                # hard in-flight cap; 0 = off
    # artifact store root (local dir, gs://..., or hf://repo)
    artifact_root: str = "/tmp/shai-artifacts"
    seed: int = 0

    @classmethod
    def from_env(cls) -> "ServeConfig":
        cfg = cls(
            app=env_str("APP", "model"),
            nodepool=env_str("NODEPOOL", "local"),
            pod_name=env_str("POD_NAME", os.uname().nodename),
            device=env_str("DEVICE", "tpu"),
            model_id=env_str("MODEL_ID", ""),
            compiled_model_id=env_str("COMPILED_MODEL_ID", ""),
            hf_token=env_str("HUGGINGFACE_TOKEN", ""),
            num_inference_steps=env_int("NUM_INFERENCE_STEPS", 25),
            num_of_runs_inf=env_int("NUM_OF_RUNS_INF", 2),
            max_new_tokens=env_int("MAX_NEW_TOKENS", 128),
            max_seq_len=env_int("MAX_SEQ_LEN", 512),
            height=env_int("HEIGHT", 512),
            width=env_int("WIDTH", 512),
            guidance_scale=env_float("GUIDANCE_SCALE", 7.5),
            batch_size=env_int("BATCH_SIZE", 1),
            scheduler=env_str("SCHEDULER", "ddim"),
            steps_buckets=env_str("STEPS_BUCKETS", ""),
            quantization=env_str("QUANTIZATION", ""),
            sd_batch_max=env_int("SD_BATCH_MAX", 1),
            vllm_config=env_str("VLLM_CONFIG", "/vllm_config.yaml"),
            mesh_spec=env_str("MESH_SPEC", ""),
            submesh=env_str("SUBMESH", ""),
            port=env_int("PORT", 8000),
            warmup=env_bool("WARMUP", True),
            metrics_port=env_int("METRICS_PORT", 9100),
            deadline_ms=env_int("DEADLINE_MS", 0),
            drain_budget_s=env_float("DRAIN_BUDGET_S", 30.0),
            admit_max_queue=env_float("ADMIT_MAX_QUEUE", 8.0),
            admit_max_kv=env_float("ADMIT_MAX_KV", 0.95),
            max_inflight=env_int("MAX_INFLIGHT", 0),
            artifact_root=env_str("ARTIFACT_ROOT", "/tmp/shai-artifacts"),
            seed=env_int("SEED", 0),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.device not in VALID_DEVICES:
            raise ValueError(
                f"DEVICE={self.device!r} not supported; expected one of {VALID_DEVICES}"
            )
        if self.height % 8 or self.width % 8:
            raise ValueError("HEIGHT and WIDTH must be multiples of 8")
        if self.batch_size < 1:
            raise ValueError("BATCH_SIZE must be >= 1")
        if self.quantization not in ("", "int8"):
            raise ValueError(
                f"QUANTIZATION={self.quantization!r} not supported; "
                f"expected '' or 'int8'")
        if self.deadline_ms < 0:
            raise ValueError("DEADLINE_MS must be >= 0 (0 disables)")
        if self.drain_budget_s < 0:
            raise ValueError("DRAIN_BUDGET_S must be >= 0")
        if self.max_inflight < 0:
            raise ValueError("MAX_INFLIGHT must be >= 0 (0 disables)")

    def describe(self) -> Dict[str, Any]:
        """Redacted config for the self-describing ``GET /`` endpoint."""
        d = dataclasses.asdict(self)
        if d.get("hf_token"):
            d["hf_token"] = "***"
        return d
