"""Int8 weight-only quantization for the causal-LM serving path.

Decode is HBM-bandwidth-bound: every generated token re-reads the full
weight set, so halving weight bytes is a near-2x tokens/sec lever on chip.
Weights are quantized per-output-channel symmetric int8; activations stay
bf16 and the scale multiplies the matmul OUTPUT — ``(x @ Wq) * scale`` is
exactly ``x @ (Wq * scale)`` because the scale is per output column, so XLA
loads int8 tiles from HBM and converts in-register instead of materializing
a dequantized copy.

The reference reaches the same capability class through the vLLM fork's
neuron quantization knob (``vllm_config.yaml`` — SURVEY.md §2.6 row 5);
here it is first-party and rides the same config contract
(``quantization: int8`` in the ConfigMap, ``engine.config.EngineConfig``).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# parent paths (dicts holding a single 2-D "kernel") that quantize; embed
# tables and norms stay high-precision
_QUANT_PARENT = re.compile(
    r"(attn/(q|k|v|o)|cross_attn/(q|k|v|o)|mlp/(gate|up|down)|lm_head)$")


def quantize_weight(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``[in, out]`` float kernel -> (int8 kernel, [out] f32 scale)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_weight(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _is_quant_node(node: Dict[str, Any], path: str) -> bool:
    """THE conversion predicate: a dict holding a single 2-D ``kernel``
    under a matmul parent path. Shared by the quantizer itself and by
    :func:`quantized_kernel_paths` (HBM budget math) so the two can never
    disagree about which leaves shrink to int8."""
    return bool(set(node) == {"kernel"} and _QUANT_PARENT.search(path)
                and getattr(node["kernel"], "ndim", 0) == 2)


def quantize_params_tree(params: Dict[str, Any]) -> Dict[str, Any]:
    """Replace every quantizable ``{"kernel": W}`` with
    ``{"kernel_q": int8, "scale": f32}`` (host-side, one pass at boot)."""

    def rec(node, path):
        if isinstance(node, dict):
            if _is_quant_node(node, path):
                q, s = quantize_weight(node["kernel"])
                return {"kernel_q": q, "scale": s}
            return {k: rec(v, f"{path}/{k}") for k, v in node.items()}
        return node

    return rec(params, "")


def quantized_kernel_paths(params: Dict[str, Any]) -> set:
    """'/'-joined leaf paths (no leading slash) that
    :func:`quantize_params_tree` would convert. Works on real arrays or an
    ``eval_shape`` tree (``ShapeDtypeStruct`` has ``ndim``) — the budget
    validator prices exactly these leaves at int8 width."""
    out: set = set()

    def rec(node, path):
        if isinstance(node, dict):
            if _is_quant_node(node, path):
                out.add(f"{path}/kernel".lstrip("/"))
                return
            for k, v in node.items():
                rec(v, f"{path}/{k}")

    rec(params, "")
    return out


def quant_matmul(x: jax.Array, p: Dict[str, jax.Array]) -> jax.Array:
    """``x @ W`` for either a plain or a quantized projection dict."""
    if "kernel_q" in p:
        y = x @ p["kernel_q"].astype(x.dtype)
        return y * p["scale"].astype(x.dtype)
    return x @ p["kernel"].astype(x.dtype)


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense(use_bias=False)`` with int8 weights.

    Param tree: ``kernel_q`` [in, out] int8 + ``scale`` [out] f32 — produced
    by :func:`quantize_params_tree` from a converted checkpoint (the zeros
    init only exists so ``init`` builds the right structure).
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel_q = self.param(
            "kernel_q", nn.initializers.zeros_init(),
            (jnp.shape(x)[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        # one copy of the dequant math — identical to the engine runner path
        return quant_matmul(x.astype(self.dtype),
                            {"kernel_q": kernel_q, "scale": scale})
