"""Int8 weight-only quantization for the causal-LM serving path.

Decode is HBM-bandwidth-bound: every generated token re-reads the full
weight set, so halving weight bytes is a near-2x tokens/sec lever on chip.
Weights are quantized per-output-channel symmetric int8; activations stay
bf16 and the scale multiplies the matmul OUTPUT — ``(x @ Wq) * scale`` is
exactly ``x @ (Wq * scale)`` because the scale is per output column, so XLA
loads int8 tiles from HBM and converts in-register instead of materializing
a dequantized copy.

The reference reaches the same capability class through the vLLM fork's
neuron quantization knob (``vllm_config.yaml`` — SURVEY.md §2.6 row 5);
here it is first-party and rides the same config contract
(``quantization: int8`` in the ConfigMap, ``engine.config.EngineConfig``).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# parent paths (dicts holding a single 2-D "kernel") that quantize; embed
# tables and norms stay high-precision
_QUANT_PARENT = re.compile(
    r"(attn/(q|k|v|o)|cross_attn/(q|k|v|o)|mlp/(gate|up|down)|lm_head)$")


def quantize_weight(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``[in, out]`` float kernel -> (int8 kernel, [out] f32 scale)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_weight(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _is_quant_node(node: Dict[str, Any], path: str) -> bool:
    """THE conversion predicate: a dict holding a single 2-D ``kernel``
    under a matmul parent path. Shared by the quantizer itself and by
    :func:`quantized_kernel_paths` (HBM budget math) so the two can never
    disagree about which leaves shrink to int8."""
    return bool(set(node) == {"kernel"} and _QUANT_PARENT.search(path)
                and getattr(node["kernel"], "ndim", 0) == 2)


def quantize_params_tree(params: Dict[str, Any]) -> Dict[str, Any]:
    """Replace every quantizable ``{"kernel": W}`` with
    ``{"kernel_q": int8, "scale": f32}`` (host-side, one pass at boot)."""

    def rec(node, path):
        if isinstance(node, dict):
            if _is_quant_node(node, path):
                q, s = quantize_weight(node["kernel"])
                return {"kernel_q": q, "scale": s}
            return {k: rec(v, f"{path}/{k}") for k, v in node.items()}
        return node

    return rec(params, "")


def quantized_kernel_paths(params: Dict[str, Any]) -> set:
    """'/'-joined leaf paths (no leading slash) that
    :func:`quantize_params_tree` would convert. Works on real arrays or an
    ``eval_shape`` tree (``ShapeDtypeStruct`` has ``ndim``) — the budget
    validator prices exactly these leaves at int8 width."""
    out: set = set()

    def rec(node, path):
        if isinstance(node, dict):
            if _is_quant_node(node, path):
                out.add(f"{path}/kernel".lstrip("/"))
                return
            for k, v in node.items():
                rec(v, f"{path}/{k}")

    rec(params, "")
    return out


# -- KV-block quantization (SHAI_KV_QUANT=int8) ------------------------------
#
# Decode batch is bounded by KV bytes, not weight bytes: the paged pool is
# the denominator of max_num_seqs x max_model_len at a fixed HBM budget.
# Per-block symmetric int8 halves it — ~2x blocks per HBM byte — with ONE
# f32 scale per (block, kv head) riding alongside (scale overhead:
# 4 / (block_size * head_dim * 2) of the saving, <1% at serving geometry).
# Quantize on pool WRITE (prefill/cont/decode scatter sites in
# engine/runner.py), dequantize on READ (in-kernel for the pallas paths,
# pre-gather for the XLA fallbacks) — the pool never holds floats.


def quantize_kv_blocks(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``[..., block_size, Hkv, Dh]`` float KV -> (int8 same shape,
    ``[..., Hkv]`` f32 scale). Symmetric per block x kv-head: the amax
    reduces over the block's token and head-dim axes only, so one head's
    outlier cannot flatten another head's resolution."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=(-3, -1))          # [..., Hkv]
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / scale[..., None, :, None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv_blocks(q: jax.Array, scale: jax.Array,
                         dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of :func:`quantize_kv_blocks`: ``[..., Bs, Hkv, Dh]`` int8 +
    ``[..., Hkv]`` f32 scale -> float blocks in ``dtype``."""
    x = q.astype(jnp.float32) * scale[..., None, :, None].astype(jnp.float32)
    return x.astype(dtype)


def requantize_block_tokens(q_blk: jax.Array, scale: jax.Array,
                            new_kv: jax.Array, pos_in_block: jax.Array
                            ) -> Tuple[jax.Array, jax.Array]:
    """Insert one fresh token's KV into an int8 block and re-quantize.

    ``q_blk`` ``[B, Bs, Hkv, Dh]`` int8 (the gathered target blocks),
    ``scale`` ``[B, Hkv]``, ``new_kv`` ``[B, Hkv, Dh]`` float, ``pos_in_block``
    ``[B]`` int32. Decode writes land one token at a time inside a block
    whose scale was fit to the tokens already there — the write must
    dequantize the block, place the token, and refit the scale (running
    max: a block's scale only grows, so earlier tokens lose at most the
    half-step of the FINAL scale, never compound past it). Returns the
    re-quantized block and its new scale.
    """
    B, Bs, _Hkv, _Dh = q_blk.shape
    x = dequantize_kv_blocks(q_blk, scale, dtype=jnp.float32)
    x = x.at[jnp.arange(B), pos_in_block].set(new_kv.astype(jnp.float32))
    tok_amax = jnp.max(jnp.abs(new_kv.astype(jnp.float32)), axis=-1)
    new_scale = jnp.maximum(scale, jnp.maximum(tok_amax, 1e-8) / 127.0)
    q = jnp.clip(jnp.round(x / new_scale[:, None, :, None]), -127, 127)
    return q.astype(jnp.int8), new_scale


def quant_matmul(x: jax.Array, p: Dict[str, jax.Array]) -> jax.Array:
    """``x @ W`` for either a plain or a quantized projection dict."""
    if "kernel_q" in p:
        y = x @ p["kernel_q"].astype(x.dtype)
        return y * p["scale"].astype(x.dtype)
    return x @ p["kernel"].astype(x.dtype)


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense(use_bias=False)`` with int8 weights.

    Param tree: ``kernel_q`` [in, out] int8 + ``scale`` [out] f32 — produced
    by :func:`quantize_params_tree` from a converted checkpoint (the zeros
    init only exists so ``init`` builds the right structure).
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel_q = self.param(
            "kernel_q", nn.initializers.zeros_init(),
            (jnp.shape(x)[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        # one copy of the dequant math — identical to the engine runner path
        return quant_matmul(x.astype(self.dtype),
                            {"kernel_q": kernel_q, "scale": scale})
