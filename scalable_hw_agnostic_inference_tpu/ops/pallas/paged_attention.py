"""Paged decode attention as a Pallas TPU kernel.

One decode step attends each sequence's KV context *directly out of the
paged block pool* through its block table — no ``[B, L, Hkv, Dh]``
materialization per layer per token (VERDICT r2 missing #3: the dense
``kflat[goff]`` gather made decode O(window) HBM traffic). The streaming is
block-table-aware:

- grid ``(B, Hkv, M)``: for each (sequence, kv head) the kernel walks the
  sequence's block table, one pool block per step, online softmax across
  steps in VMEM scratch (the flash-attention recurrence).
- the block index is *data* (scalar-prefetch): the K/V BlockSpec index maps
  read ``tables[b, j]`` to pick the physical pool block, so one compiled
  kernel serves every allocation pattern.
- blocks past a sequence's valid length re-map to its block 0; Pallas skips
  the re-fetch of an unchanged block index (revisit elision), so HBM traffic
  scales with blocks actually *used*, not the bucket window. Their scores
  are masked before the softmax update.

Reference capability this reproduces first-party: vLLM's paged attention
(``block_size: 4096`` at 128k ``max_model_len``,
``cova/mllama-32-11b-vllm-trn1-config.yaml:10-16``), which the reference
consumes from the vendored neuron fork.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _paged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, scale: float, block_size: int,
                  n_blocks: int):
    # q_ref: [Hkv, group, D]; k_ref/v_ref: [block_size, Hkv, D] — one WHOLE
    # pool block per grid step, every kv head at once. The head axis must
    # not be squeezed out of the K/V block shape: a squeezed-middle block
    # leaves Mosaic's last-two-dims tiling at (1, D), which the TPU
    # lowering rejects for every Hkv > 1 (caught by the deviceless AOT
    # compile, perf/topo.py — the kernel had only ever run in interpret
    # mode before). Streaming the full block also matches physical HBM
    # layout: a pool block's heads are contiguous, so per-head fetches of
    # the same block would not reduce traffic anyway.
    # Scratch m/l: [Hkv, group, 128], acc: [Hkv, group, D].
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lens_ref[b]
    q = q_ref[:].astype(jnp.float32) * scale          # [Hkv, G, D]
    k = k_ref[:].astype(jnp.float32)                  # [bs, Hkv, D]
    v = v_ref[:].astype(jnp.float32)
    hkv, g, _ = q.shape

    # per-kv-head 2D dots, unrolled over the static head count: Mosaic's
    # older lowerings reject batched (3D) dot_general in-kernel ("Only 2D
    # tensors supported in dot"), and Hkv here is the per-shard head count
    # (1-8), so the unroll is tiny and each dot is a clean MXU tile
    s = jnp.stack([
        jax.lax.dot_general(q[h], k[:, h, :], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        for h in range(hkv)])                         # [Hkv, G, bs]
    k_pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (hkv, g, block_size), 2)
    live = k_pos < length
    s = jnp.where(live, s, NEG_INF)

    m_prev = m_ref[:, :, :1]                          # [Hkv, G, 1]
    bm = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, bm)
    # a fully-masked block keeps m at NEG_INF: exp(NEG_INF - NEG_INF) = 1
    # would poison l/acc — zero the probabilities via the live mask instead
    p = jnp.where(live, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m_prev - m_new)                    # [Hkv, G, 1]
    l_new = l_ref[:, :, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jnp.stack([
        jax.lax.dot_general(p[h], v[:, h, :], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        for h in range(hkv)])                         # [Hkv, G, D]
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blocks - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] / jnp.maximum(l_ref[:, :, :1], 1e-20)
                    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q: jax.Array,           # [B, H, D] one query token per sequence
    k_pool: jax.Array,      # [N, block_size, Hkv, D] the paged pool
    v_pool: jax.Array,
    tables: jax.Array,      # [B, M] physical block ids (0-padded)
    lengths: jax.Array,     # [B] valid token count per sequence
    k_scale: Optional[jax.Array] = None,   # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attend each row's query over its paged context. Returns ``[B, H, D]``.

    ``tables`` may be pre-truncated to the live context bucket — the grid
    walks exactly ``M = tables.shape[1]`` blocks, and within that, re-fetch
    of dead blocks is elided (their index re-maps to the row's first block).

    ``k_scale``/``v_scale``: per-block x kv-head f32 scales of an int8 pool
    (``SHAI_KV_QUANT=int8``). The quantized bucketed call shares the ragged
    kernel body — same online-softmax recurrence with the in-kernel dequant
    and the per-row compute skip layered on; the bucketing still happens
    here, through the caller's pre-truncated ``tables``.
    """
    from jax.experimental.pallas import tpu as pltpu

    if k_scale is not None:
        from .ragged_paged_attention import ragged_paged_attention

        return ragged_paged_attention(
            q, k_pool, v_pool, tables, lengths, k_scale, v_scale,
            scale=scale, interpret=interpret)

    B, H, D = q.shape
    N, block_size, Hkv, _ = k_pool.shape
    M = tables.shape[1]
    group = H // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        from ..attention import on_tpu_platform

        interpret = not on_tpu_platform()

    tables = tables.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    # [B, Hkv, group, D]: one [group, D] q tile per (seq, kv head)
    qt = q.reshape(B, Hkv, group, D) if group > 1 else q[:, :, None, :]

    # dead blocks (j beyond the row's live count) re-map to the row's first
    # block so consecutive grid steps see an unchanged index -> no re-fetch
    def kv_index(b, j, tables, lens):
        n_live = pl.cdiv(lens[b], block_size)
        jj = jnp.where(j < jnp.maximum(n_live, 1), j, 0)
        return (tables[b, jj], 0, 0, 0)

    grid = (B, M)
    kernel = functools.partial(
        _paged_kernel, scale=scale, block_size=block_size, n_blocks=M)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, Hkv, group, D),
                             lambda b, j, tables, lens: (b, 0, 0, 0)),
                pl.BlockSpec((None, block_size, Hkv, D), kv_index),
                pl.BlockSpec((None, block_size, Hkv, D), kv_index),
            ],
            out_specs=pl.BlockSpec((None, Hkv, group, D),
                                   lambda b, j, tables, lens: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hkv, group, 128), jnp.float32),   # m
                pltpu.VMEM((Hkv, group, 128), jnp.float32),   # l
                pltpu.VMEM((Hkv, group, D), jnp.float32),     # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, D), q.dtype),
        interpret=interpret,
    )(tables, lengths, qt, k_pool, v_pool)
    return out.reshape(B, H, D)
