"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: each grid step owns one
``[BLOCK_Q, D]`` query tile in VMEM and streams K/V tiles, keeping the
``[T, S]`` score matrix out of HBM entirely. fp32 accumulators, bf16 inputs —
the MXU-friendly shape for both the SD2.1 UNet's cross/self-attention and LLM
prefill. This replaces what the reference buys from vendored runtimes
(``NEURON_FUSE_SOFTMAX=1`` fused softmax, reference ``app/compile-sd2.py:2``).

Grid layout: ``(batch, q_heads, T // BLOCK_Q)``; K/V are resident per
(batch, head) and sliced in ``BLOCK_K`` chunks inside the kernel. GQA is
handled by indexing the kv head as ``h // group`` in the BlockSpec index map —
no materialized ``jnp.repeat`` of K/V.

On CPU the same kernel runs in interpreter mode (tests); on TPU it compiles
via Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128
# lane width: head_dim and seq tiles must respect TPU tiling
_MIN_D = 64


def flash_eligible(q, k, v, mask=None, bias=None) -> bool:
    """Shapes/features the kernel covers; everything else → XLA path."""
    if mask is not None or bias is not None:
        return False
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    if D % _MIN_D or D > 256:
        return False
    if T % BLOCK_Q or S % BLOCK_K:
        return False
    if H % Hkv:
        return False
    return True


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
                  block_k: int, seq_k: int):
    # q_ref: [BLOCK_Q, D]; k_ref/v_ref: [S, D]; o_ref: [BLOCK_Q, D]
    qi = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32) * scale
    bq, d = q.shape

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    n_blocks = seq_k // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing; bound the
        # loop at the last block that can contain key <= max local query pos
        last = (qi + 1) * BLOCK_Q  # exclusive key bound
        n_live = pl.cdiv(jnp.minimum(last, seq_k), block_k)
    else:
        n_live = n_blocks

    def body(j, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * BLOCK_Q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        bm = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, bm)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, o

    m, l, o = jax.lax.fori_loop(0, n_live, body, (m0, l0, o0))
    o_ref[:] = (o / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention. q ``[B,T,H,D]``, k/v ``[B,S,Hkv,D]`` → ``[B,T,H,D]``.

    ``interpret`` defaults to True off-TPU so the same kernel runs (slowly)
    in tests on the CPU mesh.
    """
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    # kernel works in [B, H, T, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, T // BLOCK_Q)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_k=BLOCK_K, seq_k=S
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, BLOCK_Q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, S, D), lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((None, None, S, D), lambda b, h, i: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, BLOCK_Q, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
