"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: each grid step owns one
``[BLOCK_Q, D]`` query tile in VMEM and streams K/V tiles, keeping the
``[T, S]`` score matrix out of HBM entirely. fp32 accumulators, bf16 inputs —
the MXU-friendly shape for both the SD2.1 UNet's cross/self-attention and LLM
prefill. This replaces what the reference buys from vendored runtimes
(``NEURON_FUSE_SOFTMAX=1`` fused softmax, reference ``app/compile-sd2.py:2``).

Grid layout: ``(batch, q_heads, T // BLOCK_Q)``; K/V are resident per
(batch, head) and sliced in ``BLOCK_K`` chunks inside the kernel. GQA is
handled by indexing the kv head as ``h // group`` in the BlockSpec index map —
no materialized ``jnp.repeat`` of K/V.

On CPU the same kernel runs in interpreter mode (tests); on TPU it compiles
via Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128
# lane width: head_dim and seq tiles must respect TPU tiling
_MIN_D = 64
_MIN_BLOCK = 8  # smallest sublane tile the kernel will use for short T


def _pick_block(n: int, preferred: int) -> int:
    """Largest power-of-two tile ≤ preferred that divides n (≥ _MIN_BLOCK)."""
    b = preferred
    while b >= _MIN_BLOCK:
        if n % b == 0:
            return b
        b //= 2
    return 0


def flash_eligible(q, k, v, mask=None, bias=None) -> bool:
    """Shapes/features the kernel covers; everything else → XLA path.

    Per-sequence valid lengths are NOT a mask — the kernel handles them
    natively (``lengths=``), which is what lets bucketed LLM prefill (padded
    to a static bucket, true length dynamic) run on the flash path. Short
    query grids use a smaller Q tile (the SD UNet's 8x8 level, T=64), and
    ragged key counts (CLIP's S=77 cross-attention context) are padded to a
    key tile inside :func:`flash_attention` and masked via the native length
    path — neither disqualifies the kernel (VERDICT r2 weak #1a/#1b).
    """
    if mask is not None or bias is not None:
        return False
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    if D % _MIN_D or D > 256:
        return False
    if not _pick_block(T, BLOCK_Q):
        return False
    if H % Hkv:
        return False
    return True


def _flash_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float,
                  causal: bool, has_lengths: bool, block_q: int, block_k: int,
                  seq_k: int, q_offset: int):
    # lens_ref: [B] in SMEM (scalar-prefetch); q_ref: [BLOCK_Q, D];
    # k_ref/v_ref: [S, D]; o_ref: [BLOCK_Q, D]. ``q_offset`` = S - T: causal
    # queries start at key position S - T (the decode-step layout contract of
    # ``ops.attention.dot_product_attention``).
    b = pl.program_id(0)
    qi = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32) * scale
    bq, d = q.shape

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    # key blocks past the valid length contribute nothing; with causal also
    # skip blocks strictly above the diagonal. has_lengths is static: the
    # non-LLM (SD/flux) callers keep the unmasked fast path.
    if has_lengths:
        length = lens_ref[b]  # valid key count for this batch row
        bound = jnp.minimum(length, seq_k)
    else:
        length = None
        bound = seq_k
    if causal:
        bound = jnp.minimum(bound, q_offset + (qi + 1) * block_q)
    n_live = pl.cdiv(bound, block_k) if (has_lengths or causal) else (
        seq_k // block_k)

    def body(j, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        live = None
        if has_lengths or causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
        if has_lengths:
            live = k_pos < length
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            c = q_pos >= k_pos
            live = c if live is None else jnp.logical_and(live, c)
        if live is not None:
            s = jnp.where(live, s, NEG_INF)
        bm = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, bm)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, o

    m, l, o = jax.lax.fori_loop(0, n_live, body, (m0, l0, o0))
    o_ref[:] = (o / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    lengths: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention. q ``[B,T,H,D]``, k/v ``[B,S,Hkv,D]`` → ``[B,T,H,D]``.

    ``lengths`` ``[B]`` int32 marks the valid key count per row (keys beyond
    it are masked AND their blocks skipped entirely) — the bucketed-prefill
    contract: pad to the static bucket, pay for the true length. ``interpret``
    defaults to True off-TPU so the same kernel runs (slowly) in tests on the
    CPU mesh.
    """
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        from ..attention import on_tpu_platform

        interpret = not on_tpu_platform()
    block_q = _pick_block(T, BLOCK_Q)
    if not block_q:
        raise ValueError(f"T={T} not tileable (min tile {_MIN_BLOCK})")

    # Ragged key counts (e.g. CLIP context S=77) ride the native length path:
    # pad K/V up to a key tile, mask via ``lengths``. causal q_offset keeps
    # using the TRUE S — padding only ever adds masked-out keys on the right.
    q_offset = S - T
    block_k = _pick_block(S, BLOCK_K)
    if not block_k:
        s_pad = -S % _MIN_BLOCK if S < BLOCK_K else -S % BLOCK_K
        pad_len = jnp.full((B,), S, jnp.int32)
        lengths = pad_len if lengths is None else jnp.minimum(
            jnp.broadcast_to(lengths.astype(jnp.int32), (B,)), pad_len)
        k = jnp.pad(k, ((0, 0), (0, s_pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, s_pad), (0, 0), (0, 0)))
        S = S + s_pad
        block_k = _pick_block(S, BLOCK_K)

    has_lengths = lengths is not None
    if lengths is None:
        lengths = jnp.full((B,), S, jnp.int32)  # placeholder, never read
    else:
        lengths = jnp.broadcast_to(lengths.astype(jnp.int32), (B,))

    # kernel works in [B, H, T, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, T // block_q)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, has_lengths=has_lengths,
        block_q=block_q, block_k=block_k, seq_k=S, q_offset=q_offset,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, None, block_q, D),
                             lambda b, h, i, lens: (b, h, i, 0)),
                pl.BlockSpec((None, None, S, D),
                             lambda b, h, i, lens: (b, h // group, 0, 0)),
                pl.BlockSpec((None, None, S, D),
                             lambda b, h, i, lens: (b, h // group, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, block_q, D),
                                   lambda b, h, i, lens: (b, h, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(lengths, qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
