"""Ragged paged attention as a Pallas TPU kernel.

ONE dispatch for heterogeneous context lengths (PAPERS.md 2604.15464,
"Ragged Paged Attention"): every row attends exactly its own live blocks
through its block table, so the executable no longer needs a context-bucket
ladder dispatched on the LONGEST running sequence. Compared to the bucketed
kernel (``paged_attention.py``, whose grid/unroll conventions this follows):

- grid ``(B, M)`` with ``M = blocks_per_seq`` — the FULL window, compiled
  once. A short row costs what it uses, not what the longest row buckets to:
  blocks past a row's live count skip their softmax update entirely
  (``@pl.when(j < n_live)``) and re-map their K/V index to the row's block 0
  so Pallas elides the re-fetch (revisit elision). HBM traffic AND compute
  scale with tokens actually present, killing the pad waste the bucket
  ladder paid on every mixed-length batch.
- int8 KV pools (``SHAI_KV_QUANT=int8``) dequantize IN-KERNEL: the pool
  streams as int8 — half the HBM traffic of bf16 — and the per-block x
  kv-head f32 scales (``ops.quant.quantize_kv_blocks``) ride in as two tiny
  side inputs, applied right after the block load.

The XLA gather-based reference for CPU/tier-1 lives in
``ops.attention.ragged_gather_attention``; ``ops.attention.
ragged_paged_attention`` dispatches between the two so every test runs
deviceless.

Mixed-phase fused rows (``SHAI_FUSED_STEP``): because the kernel is
row-oriented — each grid row carries its own ``(table, length)`` and pays
only its own live blocks — an engine step can fuse decode and chunked
prefill into ONE dispatch by pure layout, no kernel change: the ``B``
decode rows come first (length ``pos + 1`` each), then the continuation
chunk's ``C`` queries flattened one-per-row (all sharing the chunking
sequence's table, lengths ``start + t + 1``). The kernel never learns
which phase a row belongs to; ``ops.attention.
mixed_phase_ragged_attention`` builds this layout and splits the outputs
back at row ``B``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _ragged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                   scale: float, block_size: int, n_blocks: int,
                   quantized: bool):
    # q_ref: [Hkv, group, D]; k_ref/v_ref: [block_size, Hkv, D] — one whole
    # pool block per grid step (the head axis must stay in the block shape:
    # a squeezed middle leaves Mosaic's last-two-dims tiling at (1, D),
    # rejected for Hkv > 1 — see paged_attention.py). With ``quantized``,
    # ks_ref/vs_ref [Hkv] carry the block's per-head f32 scales.
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lens_ref[b]
    n_live = pl.cdiv(length, block_size)

    # the ragged core: a dead block (j past the row's live count) does NO
    # flops — its fetch was already elided by the index re-map below, and
    # skipping the update here removes the dot/softmax work the bucketed
    # kernel still paid for masked blocks inside its window
    @pl.when(j < jnp.maximum(n_live, 1))
    def _update():
        q = q_ref[:].astype(jnp.float32) * scale      # [Hkv, G, D]
        k = k_ref[:].astype(jnp.float32)              # [bs, Hkv, D]
        v = v_ref[:].astype(jnp.float32)
        if quantized:
            # in-kernel dequant: int8 block x per-(block, head) f32 scale
            k = k * ks_ref[:][None, :, None]
            v = v * vs_ref[:][None, :, None]
        hkv, g, _ = q.shape
        # per-kv-head 2D dots unrolled over the static head count (Mosaic's
        # older lowerings reject 3D dot_general in-kernel; Hkv is the
        # per-shard head count, 1-8)
        s = jnp.stack([
            jax.lax.dot_general(q[h], k[:, h, :], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for h in range(hkv)])                     # [Hkv, G, bs]
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (hkv, g, block_size), 2)
        live = k_pos < length
        s = jnp.where(live, s, NEG_INF)
        m_prev = m_ref[:, :, :1]                      # [Hkv, G, 1]
        bm = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, bm)
        # a fully-masked tail inside a live block keeps exp() off NEG_INF
        # poison the same way the bucketed kernel does: zero via the mask
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)                # [Hkv, G, 1]
        l_new = l_ref[:, :, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jnp.stack([
            jax.lax.dot_general(p[h], v[:, h, :], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for h in range(hkv)])                     # [Hkv, G, D]
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blocks - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] / jnp.maximum(l_ref[:, :, :1], 1e-20)
                    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def ragged_paged_attention(
    q: jax.Array,           # [B, H, D] one query token per row
    k_pool: jax.Array,      # [N, block_size, Hkv, D] (float or int8 pool)
    v_pool: jax.Array,
    tables: jax.Array,      # [B, M] physical block ids (0-padded)
    lengths: jax.Array,     # [B] valid token count per row
    k_scale: Optional[jax.Array] = None,   # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attend each row's query over its OWN ragged paged context in one
    dispatch. Returns ``[B, H, D]``.

    ``tables`` spans the full window (``M = blocks_per_seq``); per-row cost
    follows ``lengths`` — dead blocks skip compute and elide their fetch.
    Multi-token callers (speculative verify, ragged continuation prefill)
    flatten their ``T`` queries into the batch axis with per-query lengths,
    exactly like the bucketed kernel's layout.
    """
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    N, block_size, Hkv, _ = k_pool.shape
    M = tables.shape[1]
    group = H // Hkv
    quantized = k_scale is not None
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        from ..attention import on_tpu_platform

        interpret = not on_tpu_platform()

    tables = tables.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    qt = q.reshape(B, Hkv, group, D) if group > 1 else q[:, :, None, :]

    # dead blocks re-map to the row's first block: consecutive grid steps
    # see an unchanged index -> no re-fetch (and no compute, via the
    # in-kernel skip)
    def kv_index(b, j, tables, lens):
        n_live = pl.cdiv(lens[b], block_size)
        jj = jnp.where(j < jnp.maximum(n_live, 1), j, 0)
        return (tables[b, jj], 0, 0, 0)

    def sc_index(b, j, tables, lens):
        n_live = pl.cdiv(lens[b], block_size)
        jj = jnp.where(j < jnp.maximum(n_live, 1), j, 0)
        return (tables[b, jj], 0)

    grid = (B, M)
    kernel = functools.partial(
        _ragged_kernel, scale=scale, block_size=block_size, n_blocks=M,
        quantized=quantized)
    in_specs = [
        pl.BlockSpec((None, Hkv, group, D),
                     lambda b, j, tables, lens: (b, 0, 0, 0)),
        pl.BlockSpec((None, block_size, Hkv, D), kv_index),
        pl.BlockSpec((None, block_size, Hkv, D), kv_index),
    ]
    args = [tables, lengths, qt, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((None, Hkv), sc_index),
                     pl.BlockSpec((None, Hkv), sc_index)]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((None, Hkv, group, D),
                                   lambda b, j, tables, lens: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hkv, group, 128), jnp.float32),   # m
                pltpu.VMEM((Hkv, group, 128), jnp.float32),   # l
                pltpu.VMEM((Hkv, group, D), jnp.float32),     # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, D), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, D)
