"""Multi-head attention with GQA, masking, and implementation dispatch.

The single attention entry point for the model zoo. On TPU the hot path is
the Pallas flash-attention kernel (``ops.pallas.flash_attention``); elsewhere
(CPU tier, tiny shapes, or shapes the kernel doesn't cover) it falls back to
a fused XLA softmax-attention with fp32 accumulation. The reference gets this
op from vendored runtimes (neuronx-cc fused softmax via ``NEURON_FUSE_SOFTMAX=1``,
reference ``app/compile-sd2.py:2``; CUDA SDPA inside diffusers) — here it is
first-party.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# platforms whose default backend is the TPU chip (the axon tunnel's PJRT
# platform registers as "tpu"; the name is kept for older plugin builds)
TPU_PLATFORMS = ("tpu", "axon")

#: every platform name SHAI_PLATFORM_OVERRIDE may legally carry — the TPU
#: names plus the PJRT backends this code can dispatch for. A typo'd or
#: truncated value would silently steer kernel dispatch; reject it here,
#: at the decision site, instead of deep inside Mosaic.
KNOWN_PLATFORMS = TPU_PLATFORMS + ("cpu", "gpu", "cuda", "rocm", "metal")

_override_logged: set = set()


def _validated_override(ovr: str) -> str:
    """Validate the override against the known platform names and log ONCE
    per value when active: a ``tpu`` override leaked into a CPU process
    (e.g. a deviceless-AOT env var inherited by a test run) otherwise
    surfaces as a Mosaic dispatch crash far from the cause."""
    if ovr not in KNOWN_PLATFORMS:
        raise ValueError(
            f"SHAI_PLATFORM_OVERRIDE={ovr!r} is not a known platform "
            f"(expected one of {', '.join(KNOWN_PLATFORMS)}); unset it or "
            f"fix the value — a wrong override steers kernel dispatch for "
            f"a device the computation will never run on")
    if ovr not in _override_logged:
        _override_logged.add(ovr)
        import logging

        logging.getLogger(__name__).warning(
            "SHAI_PLATFORM_OVERRIDE=%s active: ops dispatch follows the "
            "override, not the process backend (deviceless-AOT mode)", ovr)
    return ovr


def effective_platform() -> str:
    """Platform the CURRENT computation will actually run on.

    ``jax.default_backend()`` ignores a ``jax.default_device(...)`` override
    — ``core.aot.host_init`` runs whole-model flax inits on the CPU device
    while the global backend stays the TPU, and dispatching a Mosaic kernel
    into that CPU-placed trace crashes with "Only interpret mode is
    supported on CPU backend" (first observed on-chip in the round-5 SD
    bench). Every TPU-or-not dispatch decision in the ops layer must go
    through this helper, not ``jax.default_backend()``.

    ``SHAI_PLATFORM_OVERRIDE`` wins over everything: deviceless AOT
    compilation (``perf.topo``) traces on a CPU-backed process while
    targeting a TPU topology, so the dispatch must follow the compile
    TARGET — and must not call ``jax.default_backend()`` at all, which
    would initialize the (possibly wedged) device tunnel just to answer a
    question about a device the computation will never run on.
    """
    from ..obs.util import env_str

    ovr = env_str("SHAI_PLATFORM_OVERRIDE")
    if ovr:
        return _validated_override(ovr)
    dd = jax.config.jax_default_device
    if dd is not None:
        # the option accepts a platform STRING too (JAX_DEFAULT_DEVICE=cpu)
        return dd if isinstance(dd, str) else dd.platform
    return jax.default_backend()


def on_tpu_platform() -> bool:
    return effective_platform() in TPU_PLATFORMS

# Plain (non-causal, no-lengths) attention dispatch: measured on v5e
# (scripts/perf_attn.py), XLA's fused softmax-attention beats the flash
# kernel on every SD2.1 UNet shape — L0 self (T=S=4096, T*S=16.7M) runs
# ~2x faster through XLA (1.8ms vs 3.8ms above the sync floor). The kernel
# only wins plain attention when the [B,H,T,S] fp32 score materialization
# stops fitting comfortably in HBM (1024px-class shapes), hence a budget on
# T*S rather than a flat preference. Causal/ragged shapes always take the
# kernel: it skips key blocks past the diagonal/valid length, which XLA's
# masked softmax cannot.
_XLA_SCORE_BUDGET = 64 * 1024 * 1024

# Measured exception inside the XLA budget (scripts/perf_attn.py on v5e,
# round 3): at T*S ~ 1M (the UNet's 32x32 self-attention level, T=S=1024)
# jax's shipped block-tuned TPU flash kernel beat XLA's fused softmax
# (1163us vs 1481us above the sync floor) while losing at 16.7M (4910 vs
# 3225) and being noise at <=65k. The window dispatches exactly that level.
_JAX_FLASH_WINDOW = (2 ** 20, 2 ** 21)


def _jax_flash_eligible(q, k, mask, bias, kv_lengths, causal) -> bool:
    """Shapes jax's shipped TPU flash kernel covers: MHA, no mask/bias/
    lengths, tiling-friendly T/S, causal only when T == S (the kernel aligns
    the diagonal at 0; this API's decode offset is S - T)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    return (mask is None and bias is None and kv_lengths is None
            and H == k.shape[2] and T % 128 == 0 and S % 128 == 0
            and (not causal or T == S))


def _xla_attention(q, k, v, mask, bias, scale) -> jax.Array:
    """Reference implementation: [B,T,H,D] x [B,S,Hkv,D] -> [B,T,H,D]."""
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    if H != Hkv:
        # grouped-query attention: repeat kv heads over the group
        group = H // Hkv
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p.astype(v.dtype), v)
    return o.astype(q.dtype)


def causal_mask(T: int, S: int, offset: int = 0) -> jax.Array:
    """[1,1,T,S] boolean mask; query i attends keys j <= i + offset."""
    qi = jnp.arange(T)[:, None] + offset
    kj = jnp.arange(S)[None, :]
    return (qi >= kj)[None, None, :, :]


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Scaled dot-product attention.

    Args:
      q: ``[B, T, H, D]``.
      k, v: ``[B, S, Hkv, D]`` with ``H % Hkv == 0`` (GQA/MQA supported).
      mask: boolean, broadcastable to ``[B, H, T, S]``; True = attend.
      bias: additive, broadcastable to ``[B, H, T, S]`` (e.g. T5 relative
        position bias).
      causal: apply causal masking (assumes key block starts at position 0
        and queries start at position ``S - T``, the decode-step layout).
      scale: defaults to ``1/sqrt(D)``.
      kv_lengths: ``[B]`` int32 valid key count per row (right-padded keys
        beyond it are masked). Unlike ``mask``, this keeps the flash kernel
        eligible — it is THE way bucketed LLM prefill reaches the pallas
        path (VERDICT r1 #3).
      impl: ``auto`` (pallas on TPU when eligible), ``xla``, or ``pallas``.
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    if H % k.shape[2]:
        raise ValueError(f"q heads {H} not a multiple of kv heads {k.shape[2]}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if impl == "auto":
        # measured-dispatch escape hatch (scripts/perf_attn.py)
        from ..obs.util import env_str

        impl = env_str("SHAI_ATTN_IMPL", "auto")
        if impl == "auto" and not causal and kv_lengths is None:
            if (_jax_flash_eligible(q, k, mask, bias, kv_lengths, causal)
                    and _JAX_FLASH_WINDOW[0] <= T * S < _JAX_FLASH_WINDOW[1]
                    and on_tpu_platform()):
                impl = "jax-flash"
            elif T * S <= _XLA_SCORE_BUDGET:
                impl = "xla"

    if impl == "jax-flash":
        # jax's shipped, block-tuned TPU flash kernel (public pallas ops) —
        # a dispatch option for big self-attention shapes; needs a real TPU
        # (no interpreter mode)
        eligible = _jax_flash_eligible(q, k, mask, bias, kv_lengths, causal)
        on_tpu = on_tpu_platform()
        if eligible and on_tpu:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jax_flash,
            )

            out = jax_flash(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=causal, sm_scale=scale)
            return out.transpose(0, 2, 1, 3)
        if not eligible:
            # mirror impl="pallas": an explicit-but-ineligible request fails
            # loudly so measured dispatch tables never time the wrong path
            raise ValueError(
                f"jax-flash not eligible for q={q.shape} k={k.shape} "
                f"(mask={mask is not None}, bias={bias is not None}, "
                f"lengths={kv_lengths is not None}, causal={causal})")
        impl = "xla"  # eligible shape, no TPU: interpreter unsupported

    if impl in ("auto", "pallas"):
        # the flash kernel applies causal + length masking itself; arbitrary
        # masks and biases take the XLA path
        from .pallas.flash_attention import flash_attention, flash_eligible

        want = impl == "pallas"
        if flash_eligible(q, k, v, mask=mask, bias=bias) and (
            want or on_tpu_platform()
        ):
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   lengths=kv_lengths)
        if want:
            raise ValueError(
                f"pallas flash attention not eligible for shapes q={q.shape} "
                f"k={k.shape} (mask={mask is not None}, bias={bias is not None})"
            )
    elif impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}")

    if kv_lengths is not None:
        lm = (jnp.arange(S)[None, :]
              < kv_lengths.astype(jnp.int32)[:, None])[:, None, None, :]
        mask = lm if mask is None else jnp.logical_and(mask, lm)
    if causal:
        cm = causal_mask(T, S, offset=S - T)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    return _xla_attention(q, k, v, mask, bias, scale)


# -- ragged paged attention (reference fallback + dispatch) ------------------


def ragged_gather_attention(
    q: jax.Array,           # [B, T, H, D] queries
    k_pool: jax.Array,      # [N, block_size, Hkv, D] (float or int8 pool)
    v_pool: jax.Array,
    tables: jax.Array,      # [B, M] physical block ids (0-padded)
    positions: jax.Array,   # [B, T] each query's own cache position
    k_scale: Optional[jax.Array] = None,   # [N, Hkv] f32 (int8 pools)
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """XLA gather-based reference for ragged paged attention.

    Every query ``(b, t)`` attends pool positions ``<= positions[b, t]``
    through row ``b``'s block table — mixed context lengths in one call,
    no bucketing. This is THE deviceless oracle for the Pallas ragged
    kernel (``ops.pallas.ragged_paged_attention``): a dense gather of the
    table window plus a per-query mask, exactly the engine's pre-ragged
    CPU decode path, so quant-off numerics are bit-identical to it. int8
    pools dequantize right after the gather (``ops.quant``). Returns
    ``[B, T, H, D]``.
    """
    B, T, H, D = q.shape
    _N, block_size, Hkv, _ = k_pool.shape
    M = tables.shape[1]
    L = M * block_size
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if k_scale is not None:
        from .quant import dequantize_kv_blocks

        # block-shaped gather so the per-(block, head) scales broadcast;
        # the reshape lands in the same [B, L, Hkv, D] layout as the flat
        # gather below
        kctx = dequantize_kv_blocks(
            k_pool[tables], k_scale[tables], dtype=q.dtype
        ).reshape(B, L, Hkv, D)
        vctx = dequantize_kv_blocks(
            v_pool[tables], v_scale[tables], dtype=q.dtype
        ).reshape(B, L, Hkv, D)
    else:
        goff = (tables[:, :, None] * block_size
                + jnp.arange(block_size)[None, None, :]).reshape(B, L)
        kflat = k_pool.reshape(-1, Hkv, D)
        vflat = v_pool.reshape(-1, Hkv, D)
        kctx = kflat[goff]
        vctx = vflat[goff]
    mask = (jnp.arange(L)[None, None, :]
            <= positions[:, :, None])[:, None]         # [B, 1, T, L]
    return _xla_attention(q, kctx, vctx, mask, None, scale)


def ragged_paged_attention(
    q: jax.Array,           # [rows, H, D] one query per row
    k_pool: jax.Array,
    v_pool: jax.Array,
    tables: jax.Array,      # [rows, M]
    lengths: jax.Array,     # [rows] valid token count per row
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ragged paged attention with implementation dispatch: the Pallas
    kernel on TPU platforms, the XLA gather reference elsewhere (tier-1
    runs deviceless). Multi-token callers flatten ``T`` queries into the
    row axis with per-row ``lengths``, the same layout both impls share
    with the bucketed kernel."""
    if on_tpu_platform():
        from .pallas.ragged_paged_attention import (
            ragged_paged_attention as _kernel,
        )

        return _kernel(q, k_pool, v_pool, tables, lengths, k_scale,
                       v_scale, scale=scale)
    out = ragged_gather_attention(
        q[:, None], k_pool, v_pool, tables,
        (lengths.astype(jnp.int32) - 1)[:, None], k_scale, v_scale,
        scale=scale)
    return out[:, 0]


def mixed_phase_ragged_attention(
    q_dec: jax.Array,       # [B, H, D] decode queries, one per slot row
    q_chunk: jax.Array,     # [C, H, D] continuation-chunk queries (1 seq)
    k_pool: jax.Array,
    v_pool: jax.Array,
    tables_dec: jax.Array,  # [B, M] per-slot block tables
    c_table: jax.Array,     # [1, M] the chunking sequence's table
    pos_dec: jax.Array,     # [B] each decode row's own cache position
    c_pos: jax.Array,       # [C] per-chunk-query cache positions
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    pool_call=None,
):
    """Mixed-phase ragged attention (``SHAI_FUSED_STEP``): ``B`` decode
    rows and one ``C``-token continuation chunk attend the paged pool in
    ONE ragged dispatch.

    The ragged kernel is already row-oriented — every row carries its own
    ``(table, length)`` and pays only its own live blocks — so phases fuse
    by pure layout: the chunk's ``C`` single-query rows are appended after
    the ``B`` decode rows (chunk rows share one block table, repeated),
    lengths are each query's ``position + 1``, and the kernel never learns
    which phase a row belongs to. The outputs split back at row ``B``:
    ``(o_dec [B, H, D], o_chunk [C, H, D])``.

    ``pool_call`` is the caller's pre-bound dispatch seam (the engine
    passes ``runner._pool_kernel_call`` closed over the kernel and the TP
    shardings); when ``None`` the rows go through
    :func:`ragged_paged_attention` — Pallas on TPU, the XLA gather oracle
    elsewhere — which is the path the fused-vs-laddered exactness tests
    pin first (gather oracle before kernel).
    """
    B, _H, _D = q_dec.shape
    C = q_chunk.shape[0]
    M = tables_dec.shape[1]
    block_size = k_pool.shape[1]
    L = M * block_size
    qf = jnp.concatenate([q_dec, q_chunk], axis=0)
    tf = jnp.concatenate([tables_dec, jnp.repeat(c_table, C, axis=0)],
                         axis=0)
    lf = jnp.clip(jnp.concatenate([pos_dec, c_pos]) + 1, 1, L).astype(
        jnp.int32)
    if pool_call is None:
        of = ragged_paged_attention(qf, k_pool, v_pool, tf, lf, k_scale,
                                    v_scale, scale=scale)
    else:
        of = pool_call(qf, k_pool, v_pool, tf, lf, k_scale, v_scale)
    return of[:B], of[B:]
