"""Rotary position embeddings (Llama/Mistral-style half-rotation).

Pure functions over ``[B, T, H, D]`` tensors; positions are explicit so the
same code serves prefill (positions ``0..T``) and paged decode (arbitrary
per-token positions from the block table) without recompilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def llama3_scaled_inv_freq(inv_freq: jax.Array, scaling) -> jax.Array:
    """HF ``rope_type="llama3"`` frequency remap (Llama-3.1+/mllama).

    ``scaling`` = (factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings). Long wavelengths divide by ``factor``,
    short ones pass through, the band between interpolates smoothly.
    """
    import math

    factor, low, high, orig = scaling
    low_wavelen = orig / low
    high_wavelen = orig / high
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / factor
    smooth = (orig / wavelen - low) / (high - low)
    mid = (1 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_wavelen, scaled, inv_freq)
    is_mid = jnp.logical_and(wavelen <= low_wavelen, wavelen >= high_wavelen)
    return jnp.where(is_mid, mid, out)


def rope_angles(positions: jax.Array, dim: int, theta: float = 10000.0,
                scaling=None):
    """cos/sin tables for ``positions`` → each ``[..., dim/2]`` (fp32).

    ``scaling``: optional llama3 rope-scaling tuple (see
    :func:`llama3_scaled_inv_freq`).
    """
    if dim % 2:
        raise ValueError(f"rope dim must be even, got {dim}")
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    if scaling is not None:
        inv_freq = llama3_scaled_inv_freq(inv_freq, scaling)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., dim/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, positions: jax.Array, theta: float = 10000.0,
               scaling=None) -> jax.Array:
    """Rotate ``x`` ``[B, T, H, D]`` by per-token ``positions`` ``[B, T]``.

    Half-rotation convention (HF Llama): the first D/2 lanes pair with the
    last D/2 lanes.
    """
    B, T, H, D = x.shape
    cos, sin = rope_angles(positions, D, theta, scaling)  # [B, T, D/2]
    cos = cos[:, :, None, :]  # [B, T, 1, D/2]
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
