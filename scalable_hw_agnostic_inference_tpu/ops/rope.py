"""Rotary position embeddings (Llama/Mistral-style half-rotation).

Pure functions over ``[B, T, H, D]`` tensors; positions are explicit so the
same code serves prefill (positions ``0..T``) and paged decode (arbitrary
per-token positions from the block table) without recompilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, dim: int, theta: float = 10000.0):
    """cos/sin tables for ``positions`` → each ``[..., dim/2]`` (fp32)."""
    if dim % 2:
        raise ValueError(f"rope dim must be even, got {dim}")
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., dim/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, positions: jax.Array, theta: float = 10000.0) -> jax.Array:
    """Rotate ``x`` ``[B, T, H, D]`` by per-token ``positions`` ``[B, T]``.

    Half-rotation convention (HF Llama): the first D/2 lanes pair with the
    last D/2 lanes.
    """
    B, T, H, D = x.shape
    cos, sin = rope_angles(positions, D, theta)  # [B, T, D/2]
    cos = cos[:, :, None, :]  # [B, T, 1, D/2]
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
