"""Normalization ops shared across model families (llama, t5, flux).

RMSNorm computes in fp32 regardless of compute dtype — matching the HF/Llama
convention so converted checkpoints are numerically comparable. The reference
gets these from vendored torch modules; here they are first-party and fuse
into neighbouring matmuls under XLA.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class RMSNorm(nn.Module):
    """Root-mean-square LayerNorm (no mean subtraction, no bias).

    ``scale_offset=1.0`` gives the Gemma convention (param stored as
    ``scale - 1``); 0.0 (default) is the Llama/T5 convention.
    """

    eps: float = 1e-6
    dtype: Any = jnp.float32
    scale_offset: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (y * (scale + self.scale_offset)).astype(self.dtype)
