"""Compute ops: attention, rotary embeddings, sampling, Pallas TPU kernels.

Array convention for attention-family ops is ``[batch, seq, heads, head_dim]``
(the flax layout). The shard_map-level sequence-parallel ops in
``parallel.ring`` use ``[batch, heads, seq, head_dim]`` — transpose at the
boundary.
"""

from .attention import dot_product_attention  # noqa: F401
from .rope import rope_angles, apply_rope  # noqa: F401
from .sampling import sample_logits, greedy  # noqa: F401
