"""On-device token sampling: temperature, top-k, top-p, greedy.

The reference's LLM path samples on-device inside the vLLM/NxD engine
(``global_topk: 64, "dynamic"``, reference
``cova/mllama-32-11b-vllm-trn1-config.yaml:18-22``). These are the jit-safe
equivalents the TPU engine composes into its decode step — no host round-trip
between logits and the sampled token. All knobs may be scalars or per-request
arrays (one entry per row of a continuous batch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def greedy(logits: jax.Array) -> jax.Array:
    """Argmax over the vocab dim. logits ``[..., V]`` → tokens ``[...]``."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _mask_top_k(logits: jax.Array, k: jax.Array) -> jax.Array:
    """Keep the top ``k`` logits per row; ``k`` ``[...]`` (0 = off)."""
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    k_eff = jnp.clip(k, 1, V)
    thresh = jnp.take_along_axis(sorted_desc, (k_eff - 1)[..., None], axis=-1)
    masked = jnp.where(logits >= thresh, logits, NEG_INF)
    return jnp.where((k > 0)[..., None], masked, logits)


def _mask_top_p(logits: jax.Array, p: jax.Array) -> jax.Array:
    """Nucleus sampling mask; ``p`` ``[...]`` in (0, 1] (1 = off)."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob (exclusive of self) < p; this always
    # keeps the top-1 token
    keep_sorted = (cum - probs) < p[..., None]
    kth = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
    thresh = jnp.take_along_axis(sorted_desc, jnp.clip(kth, 0, None), axis=-1)
    masked = jnp.where(logits >= thresh, logits, NEG_INF)
    return jnp.where((p >= 1.0)[..., None], logits, masked)


def _broadcast_knobs(logits, temperature, top_k, top_p):
    batch_shape = logits.shape[:-1]
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), batch_shape)
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), batch_shape)
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), batch_shape)
    return t, k, p


def masked_scaled_logits(
    logits: jax.Array,
    temperature: float | jax.Array = 1.0,
    top_k: int | jax.Array = 0,
    top_p: float | jax.Array = 1.0,
) -> jax.Array:
    """The post-temperature/top-k/top-p logits ``sample_logits`` draws from
    (categorical over these == the actual sampling distribution)."""
    t, k, p = _broadcast_knobs(logits, temperature, top_k, top_p)
    scaled = logits.astype(jnp.float32) / jnp.maximum(t, 1e-6)[..., None]
    return _mask_top_p(_mask_top_k(scaled, k), p)


def sample_excluding(
    logits: jax.Array,
    rng: jax.Array,
    exclude: jax.Array,
    temperature: float | jax.Array = 1.0,
    top_k: int | jax.Array = 0,
    top_p: float | jax.Array = 1.0,
) -> jax.Array:
    """Sample from the :func:`sample_logits` distribution with token
    ``exclude`` ``[...]`` removed — speculative decoding's rejection
    resample (the residual of a delta proposal is the target distribution
    with the rejected token zeroed, renormalized over the ORIGINAL
    support). The top-k/top-p masks are computed BEFORE the exclusion:
    recomputing them after would let a rank-(k+1) token into the support,
    emitting tokens vanilla sampling can never produce.
    """
    t, _, _ = _broadcast_knobs(logits, temperature, top_k, top_p)
    hole = exclude[..., None] == jnp.arange(logits.shape[-1])[None]
    masked = jnp.where(hole, NEG_INF,
                       masked_scaled_logits(logits, temperature, top_k, top_p))
    sampled = jax.random.categorical(rng, masked, axis=-1).astype(jnp.int32)
    # temperature 0: the argmax with the excluded token removed (raw logits
    # — greedy has full support minus the hole)
    return jnp.where(t <= 0.0, greedy(jnp.where(hole, NEG_INF, logits)),
                     sampled)


def sampling_probs(
    logits: jax.Array,
    temperature: float | jax.Array = 1.0,
    top_k: int | jax.Array = 0,
    top_p: float | jax.Array = 1.0,
) -> jax.Array:
    """The ACTUAL sampling distribution ``[..., V]`` — post temperature,
    top-k and top-p, the distribution :func:`sample_logits` draws from
    (a point mass on the argmax at ``temperature == 0``).

    Speculative decoding's rejection rule needs this exactly: a draft token
    is accepted with its probability under the real sampling distribution,
    not under the raw softmax — a draft outside the nucleus must always be
    rejected, or verification would commit tokens vanilla decode can never
    emit.
    """
    t, _, _ = _broadcast_knobs(logits, temperature, top_k, top_p)
    probs = jax.nn.softmax(
        masked_scaled_logits(logits, temperature, top_k, top_p), axis=-1)
    point = jax.nn.one_hot(greedy(logits), logits.shape[-1],
                           dtype=jnp.float32)
    return jnp.where((t <= 0.0)[..., None], point, probs)


def sample_logits(
    logits: jax.Array,
    rng: jax.Array,
    temperature: float | jax.Array = 1.0,
    top_k: int | jax.Array = 0,
    top_p: float | jax.Array = 1.0,
) -> jax.Array:
    """Sample tokens from ``[..., V]`` logits. Jit-safe; all knobs traceable.

    ``temperature == 0`` selects greedy decoding (per-row when the knob is a
    per-request array in a continuous batch).
    """
    t, _, _ = _broadcast_knobs(logits, temperature, top_k, top_p)
    masked = masked_scaled_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(rng, masked, axis=-1).astype(jnp.int32)
    return jnp.where(t <= 0.0, greedy(logits), sampled)
