"""Jitted engine paths: bucketed prefill + one decode step for the batch.

The runner consumes the SAME parameter pytree as
``models.llama.LlamaForCausalLM`` (one weight story: HF convert → orbax →
either the plain server or this engine) but re-plumbs the forward around the
paged KV pool — prefill scatters whole blocks, decode writes one token per
slot and gathers per-slot context through block tables. The reference gets
all of this from the vLLM fork's neuron backend (SURVEY.md §2.6 row 5);
TPU-natively it is two compiled executables per bucket, shapes static.

Decode is ONE executable for the whole running batch: [B] tokens in,
[B] sampled tokens out, sampling on device (reference parity:
``on_device_sampling_config`` ``global_topk: 64``,
``cova/mllama-32-11b-vllm-trn1-config.yaml:19-22``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig
from ..ops.attention import dot_product_attention
from ..ops.quant import quant_matmul
from ..ops.rope import apply_rope
from ..ops.sampling import (
    sample_excluding,
    sample_logits,
    sampling_probs,
)


class EngineShardings:
    """Tensor-parallel placement plan for the engine's two executables.

    The reference's TP=32 serving tier comes from the vLLM/NxD fork
    (``compile-vllm-job.yaml:54-55``); here it is in_shardings on the jitted
    prefill/decode — params per ``models.llama.tp_rules``, the paged KV pool
    split on its kv-head axis (``cache_specs``) — and XLA inserts the
    collectives over the ``tp`` mesh axis.
    """

    def __init__(self, mesh, params, cfg: LlamaConfig):
        from ..models.llama import cache_specs, tp_rules

        tp = mesh.shape.get("tp", 1)
        # fail loudly at construction: a GQA config whose head counts don't
        # divide tp would otherwise surface as an opaque partitioning error
        # deep inside the first jitted call
        if cfg.n_kv_heads % tp or cfg.n_heads % tp:
            raise ValueError(
                f"tensor_parallel_size={tp} must divide both n_heads="
                f"{cfg.n_heads} and n_kv_heads={cfg.n_kv_heads}. For GQA "
                f"models with tp > n_kv_heads (the reference's 70B TP=32 "
                f"tier), widen the kv heads first with "
                f"models.llama.replicate_kv_heads(params, cfg, tp) — the "
                f"serve layer does this automatically (units/vllm.py)")
        self.mesh = mesh
        self.rep = NamedSharding(mesh, P())
        specs = tp_rules().tree_specs(params)
        self.params = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        kvspec = cache_specs(cfg, axis_size=mesh.shape.get("tp", 1))
        self.kv_layer = {n: NamedSharding(mesh, s) for n, s in kvspec.items()}
        # int8 KV pools (SHAI_KV_QUANT): the per-(block, head) scale arrays
        # [N, Hkv] split on the same kv-head axis as the blocks they scale
        self.kv_scale = NamedSharding(mesh, P(None, "tp"))

    def kv_pool(self, n_layers: int, quant: bool = False):
        if quant:
            return [{**self.kv_layer,
                     "ks": self.kv_scale, "vs": self.kv_scale}
                    for _ in range(n_layers)]
        return [dict(self.kv_layer) for _ in range(n_layers)]

    def cross_pool(self, n_cross: int):
        # mllama cross-kv buffers [B, Lv, Hkv, Dh]: split on the kv-head
        # axis, same placement as the paged pool
        spec = NamedSharding(self.mesh, P(None, None, "tp", None))
        return [{"k": spec, "v": spec} for _ in range(n_cross)]


def _rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (n * scale).astype(x.dtype)


def _proj(x: jax.Array, p: Dict[str, jax.Array]) -> jax.Array:
    # plain or int8 weight-only projections (ops.quant): decode re-reads all
    # weights per token, so int8 halves its HBM traffic
    return quant_matmul(x, p)


def _qkv(lp: Dict, x: jax.Array, positions: jax.Array, cfg: LlamaConfig):
    B, T, _ = x.shape
    Dh = cfg.head_dim
    q = _proj(x, lp["attn"]["q"]).reshape(B, T, cfg.n_heads, Dh)
    k = _proj(x, lp["attn"]["k"]).reshape(B, T, cfg.n_kv_heads, Dh)
    v = _proj(x, lp["attn"]["v"]).reshape(B, T, cfg.n_kv_heads, Dh)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    return q, k, v


def _mlp(lp: Dict, x: jax.Array) -> jax.Array:
    gate = _proj(x, lp["mlp"]["gate"])
    up = _proj(x, lp["mlp"]["up"])
    return _proj(jax.nn.silu(gate) * up, lp["mlp"]["down"])


def _head_rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """RMSNorm over the head dim of ``[B, T, H, Dh]`` (mllama q/k norms)."""
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (n * scale).astype(x.dtype)


# top-N alternatives reported per sampled token when a request asks for
# logprobs (the OpenAI `logprobs` field; 5 is the classic completions cap)
K_LOGPROBS = 5


def token_logprobs(logits: jax.Array, toks: jax.Array):
    """``[B, V]`` raw logits + ``[B]`` sampled ids → per-token logprob data:
    ``(top_ids [B, K], top_logprobs [B, K], sampled_logprob [B])``. Raw
    (pre-temperature) distribution — what the OpenAI field reports."""
    logp = logits - jax.scipy.special.logsumexp(logits, axis=-1,
                                                keepdims=True)
    top_lp, top_ids = jax.lax.top_k(logp, K_LOGPROBS)
    tok_lp = jnp.take_along_axis(logp, toks[:, None], axis=1)[:, 0]
    return top_ids.astype(jnp.int32), top_lp, tok_lp


def make_cross_kv(cfg: LlamaConfig):
    """Compile ``cross_kv(params, states [Lv, dim]) -> [n_cross] x {k, v}``.

    The per-request half of mllama cross-attention: project (and k-norm) the
    vision states ONCE at admission; prefill/decode then read the projected
    k/v from slot-indexed buffers every step (vLLM's encoder-cache idea).
    HF recomputes this lazily inside ``MllamaTextCrossAttention`` (
    reference capability: ``cova/mllama-32-11b-vllm-trn1-config.yaml``).
    """

    def cross_kv(params, states):
        p = params["params"]
        out = []
        x = states[None].astype(jnp.bfloat16)      # [1, Lv, dim]
        for li in cfg.cross_attention_layers:
            lp = p[f"layer_{li}"]["cross_attn"]
            Lv = x.shape[1]
            k = _proj(x, lp["k"]).reshape(1, Lv, cfg.n_kv_heads, cfg.head_dim)
            v = _proj(x, lp["v"]).reshape(1, Lv, cfg.n_kv_heads, cfg.head_dim)
            k = _head_rmsnorm(k, lp["k_norm"]["scale"], cfg.rms_eps)
            out.append({"k": k[0], "v": v[0]})
        return out

    return jax.jit(cross_kv)


def make_cross_slot_write(cfg: LlamaConfig):
    """Compile ``write(cross_kv, per_layer, slot) -> cross_kv`` — all cross
    layers' slot rows updated in ONE donated-buffer call (2*n_cross
    host-dispatched full-buffer copies otherwise; ~400MB per admission at
    11B scale)."""

    def write(cross_kv, per_layer, slot):
        out = []
        for buf, new in zip(cross_kv, per_layer):
            out.append({
                "k": buf["k"].at[slot].set(new["k"].astype(buf["k"].dtype)),
                "v": buf["v"].at[slot].set(new["v"].astype(buf["v"].dtype)),
            })
        return out

    return jax.jit(write, donate_argnums=(0,))


def _tp_attention(shardings: Optional["EngineShardings"], q, k, v, *,
                  kv_lengths=None, causal=False):
    """Self/cross attention, head-split over ``tp`` via shard_map under TP.

    The flash kernel behind ``dot_product_attention`` (``ops.pallas``) is a
    raw Mosaic call — XLA's SPMD partitioner refuses to split it
    automatically ("Mosaic kernels cannot be automatically partitioned"), so
    a TP-sharded prefill would fail to COMPILE on the first multi-chip boot.
    Attention is head-local, so under TP the call is explicitly shard_map'd
    on the head axes; contiguous head splits keep every GQA group on its
    rank (``EngineShardings`` enforces tp | n_heads and tp | n_kv_heads,
    widening GQA kv heads by replication when tp is larger —
    ``models.llama.replicate_kv_heads``). Single-device engines call
    straight through. Caught by the tp=32 abstract lowering leg
    (``__graft_entry__.dryrun_lower_llama70b_tp32``).
    """
    if shardings is None:
        return dot_product_attention(q, k, v, kv_lengths=kv_lengths,
                                     causal=causal)
    from jax.experimental.shard_map import shard_map

    heads = P(None, None, "tp", None)
    if kv_lengths is None:
        return shard_map(
            lambda q_, k_, v_: dot_product_attention(q_, k_, v_,
                                                     causal=causal),
            mesh=shardings.mesh, in_specs=(heads,) * 3, out_specs=heads,
            check_rep=False,
        )(q, k, v)
    return shard_map(
        lambda q_, k_, v_, n_: dot_product_attention(
            q_, k_, v_, kv_lengths=n_, causal=causal),
        mesh=shardings.mesh,
        in_specs=(heads, heads, heads, P(None)),
        out_specs=heads,
        check_rep=False,
    )(q, k, v, kv_lengths)


def _cross_layer(lp: Dict, x: jax.Array, cross_k: jax.Array,
                 cross_v: jax.Array, has_image: jax.Array,
                 cfg: LlamaConfig, cross_len=None,
                 shardings: Optional["EngineShardings"] = None) -> jax.Array:
    """One mllama gated cross-attention layer.

    ``x`` [B, T, dim]; ``cross_k/v`` [B, Lv, Hkv, Dh] (already k-normed);
    ``has_image`` [B] float gate — rows without vision states contribute
    nothing, which is exactly HF's skip-the-layer semantics for text-only
    requests through an mllama checkpoint. ``cross_len`` [B] marks the valid
    vision-token count per row (multi-tile images use a tile-count-dependent
    prefix of the static Lv buffer; the rest is masked).
    """
    B, T, _ = x.shape
    ca = lp["cross_attn"]
    h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
    q = _proj(h, ca["q"]).reshape(B, T, cfg.n_heads, cfg.head_dim)
    q = _head_rmsnorm(q, ca["q_norm"]["scale"], cfg.rms_eps)
    o = _tp_attention(shardings, q, cross_k.astype(q.dtype),
                      cross_v.astype(q.dtype), kv_lengths=cross_len)
    # gate in x's dtype: an f32 gate would promote the residual stream (and
    # every downstream layer) off bf16
    gate = has_image.astype(x.dtype)[:, None, None]
    g_attn = jnp.tanh(lp["gate_attn"]).astype(x.dtype)
    g_mlp = jnp.tanh(lp["gate_mlp"]).astype(x.dtype)
    x = x + g_attn * _proj(o.reshape(B, T, -1), ca["o"]) * gate
    m = _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"], cfg.rms_eps))
    return x + g_mlp * m * gate


def _scatter_blocks(kv_layer: Dict, tbl: jax.Array, k: jax.Array,
                    v: jax.Array, quant: bool) -> Dict:
    """Scatter whole fresh KV blocks ``[B, m, Bs, Hkv, Dh]`` into one pool
    layer. int8 pools (``SHAI_KV_QUANT``) quantize per block x kv-head on
    the way in (``ops.quant.quantize_kv_blocks``) and scatter the f32
    scales alongside — THE quantized-write seam every prefill/continuation
    scatter goes through."""
    if quant:
        from ..ops.quant import quantize_kv_blocks

        kq, ksc = quantize_kv_blocks(k)
        vq, vsc = quantize_kv_blocks(v)
        return {"k": kv_layer["k"].at[tbl].set(kq),
                "v": kv_layer["v"].at[tbl].set(vq),
                "ks": kv_layer["ks"].at[tbl].set(ksc),
                "vs": kv_layer["vs"].at[tbl].set(vsc)}
    return {"k": kv_layer["k"].at[tbl].set(k.astype(kv_layer["k"].dtype)),
            "v": kv_layer["v"].at[tbl].set(v.astype(kv_layer["v"].dtype))}


def _pool_scales(kv_layer: Dict):
    """``(k_scale, v_scale)`` of an int8 pool layer, ``(None, None)`` for a
    float pool — the read-side twin of :func:`_scatter_blocks`."""
    return kv_layer.get("ks"), kv_layer.get("vs")


def _logits(p: Dict, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    x = _rmsnorm(x, p["final_norm"]["scale"], cfg.rms_eps)
    if cfg.tie_embeddings:
        return (x.astype(jnp.float32) @ p["embed"]["embedding"].T)
    return _proj(x, p["lm_head"]).astype(jnp.float32)


def make_prefill(cfg: LlamaConfig, block_size: int, blocks_per_seq: int,
                 bucket: int, prefix_len: int = 0, n_seqs: int = 1,
                 shardings: Optional[EngineShardings] = None,
                 kv_quant: bool = False):
    """Compile ``prefill(params, kv, ids, n, block_tables[, prefix])``.

    ``n_seqs`` sequences per call: ``ids`` ``[K, bucket - prefix_len]``
    right-padded text with true lengths ``n_text`` ``[K]``, block tables
    ``[K, blocks_per_seq]``. Batching prefills is what keeps K queued prompts
    from each stalling the decode batch serially (VERDICT r2 weak #4) — the
    scheduler admits a same-bucket group and pays ONE executable call. Rows
    beyond the admitted group carry a null block table (all zeros) and write
    harmlessly into reserved block 0. With ``prefix_len > 0`` a ``prefix``
    ``[K, prefix_len, dim]`` of soft embeddings (vision tokens — the
    multimodal path, reference ``vllm_model_api_m.py:42-66``) occupies the
    first positions. k/v for the whole bucket are scattered into the pool;
    pad positions land in the null block and stay masked forever by the
    sequence length. Returns next-token logits from the last valid position
    of each row.
    """
    assert bucket % block_size == 0
    assert 0 <= prefix_len < bucket
    m_used = bucket // block_size
    cross_set = set(cfg.cross_attention_layers)

    def _prefill_impl(params, kv, ids, n_text, block_tables, prefix=None,
                      cross_kv=None, has_image=None, cross_len=None):
        p = params["params"]
        B = ids.shape[0]  # == n_seqs
        x = p["embed"]["embedding"][ids].astype(jnp.bfloat16)
        if prefix_len:
            x = jnp.concatenate([prefix.astype(jnp.bfloat16), x], axis=1)
        T = x.shape[1]  # == bucket
        n = n_text + prefix_len
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        tbl = block_tables[:, :m_used]  # [B, m_used]
        ci = 0
        pi = 0  # pool index: cross layers own no KV pool entries
        for li in range(cfg.n_layers):
            lp = p[f"layer_{li}"]
            if li in cross_set:
                # gated cross-attention over vision states: no rope, no KV
                # pool traffic — its keys are static per request
                x = _cross_layer(lp, x, cross_kv[ci]["k"], cross_kv[ci]["v"],
                                 has_image, cfg, cross_len=cross_len,
                                 shardings=shardings)
                ci += 1
                continue
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
            q, k, v = _qkv(lp, h, positions, cfg)
            # causal within the prompt; pad keys masked by the true length —
            # kv_lengths (not a mask) keeps the pallas flash kernel eligible
            # for bucketed prefill shapes (VERDICT r1 #3); head-split
            # shard_map under TP (the raw Mosaic kernel cannot be
            # auto-partitioned)
            o = _tp_attention(shardings, q, k, v, kv_lengths=n, causal=True)
            x = x + _proj(o.reshape(B, T, -1), lp["attn"]["o"])
            x = x + _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"], cfg.rms_eps))
            # scatter each row's k/v blocks into the pool ([B, m_used]
            # index); int8 pools quantize per block x head on the way in
            kv[pi] = _scatter_blocks(
                kv[pi], tbl,
                k.reshape(B, m_used, block_size, cfg.n_kv_heads,
                          cfg.head_dim),
                v.reshape(B, m_used, block_size, cfg.n_kv_heads,
                          cfg.head_dim), kv_quant)
            pi += 1
        last = jnp.take_along_axis(x, (n - 1).reshape(B, 1, 1), axis=1)
        return kv, _logits(p, last, cfg)[:, 0]  # [B, V]

    # positional signature per variant (in_shardings needs positional args)
    if cross_set:
        assert not prefix_len, "mllama prefill: cross states, not soft prefix"

        def prefill(params, kv, ids, n_text, block_tables, cross_kv,
                    has_image, cross_len):
            return _prefill_impl(params, kv, ids, n_text, block_tables,
                                 cross_kv=cross_kv, has_image=has_image,
                                 cross_len=cross_len)
    elif prefix_len:
        def prefill(params, kv, ids, n_text, block_tables, prefix):
            return _prefill_impl(params, kv, ids, n_text, block_tables,
                                 prefix=prefix)
    else:
        def prefill(params, kv, ids, n_text, block_tables):
            return _prefill_impl(params, kv, ids, n_text, block_tables)

    if shardings is None:
        return jax.jit(prefill, donate_argnums=(1,))
    sh, rep = shardings, shardings.rep
    kvsh = sh.kv_pool(cfg.n_layers - len(cross_set), quant=kv_quant)
    in_sh = [sh.params, kvsh, rep, rep, rep]
    if cross_set:
        in_sh += [sh.cross_pool(len(cross_set)), rep, rep]
    elif prefix_len:
        in_sh += [rep]
    return jax.jit(prefill, donate_argnums=(1,),
                   in_shardings=tuple(in_sh), out_shardings=(kvsh, rep))


def _pool_kernel_call(kernel, shardings: Optional["EngineShardings"],
                      qf, kpool, vpool, tf, lf, ks=None, vs=None):
    """THE dispatch seam for a paged/ragged pool kernel on flattened rows:
    direct call on one device, head-split shard_map under TP (the raw
    Mosaic kernel cannot be auto-partitioned; attention is head-local so
    the split needs no collectives). int8 scale arrays ride along when
    present, split on the same kv-head axis as the blocks they scale.
    Shared by decode/verify (``_make_token_forward``) and the ragged
    continuation (``_ragged_pool_attention``) so the sharding specs can
    never diverge between the two."""
    if shardings is None:
        return kernel(qf, kpool, vpool, tf, lf, ks, vs)
    from jax.experimental.shard_map import shard_map

    heads_q = P(None, "tp", None)
    heads_kv = P(None, None, "tp", None)
    if ks is None:
        return shard_map(
            lambda q_, k_, v_, t_, l_: kernel(q_, k_, v_, t_, l_),
            mesh=shardings.mesh,
            in_specs=(heads_q, heads_kv, heads_kv, P(None, None), P(None)),
            out_specs=heads_q, check_rep=False,
        )(qf, kpool, vpool, tf, lf)
    return shard_map(
        lambda q_, k_, v_, t_, l_, ks_, vs_: kernel(
            q_, k_, v_, t_, l_, ks_, vs_),
        mesh=shardings.mesh,
        in_specs=(heads_q, heads_kv, heads_kv, P(None, None), P(None),
                  P(None, "tp"), P(None, "tp")),
        out_specs=heads_q, check_rep=False,
    )(qf, kpool, vpool, tf, lf, ks, vs)


def _ragged_pool_attention(q: jax.Array, kv_layer: Dict, tables: jax.Array,
                           positions: jax.Array, block_size: int,
                           shardings: Optional["EngineShardings"]):
    """Ragged attention of ``[B, T, H, D]`` queries over the paged pool:
    the Pallas ragged kernel on TPU platforms (``T`` queries flattened
    into the row axis, through the shared ``_pool_kernel_call`` dispatch
    seam), the XLA gather reference elsewhere (which XLA partitions
    automatically). int8 pool scales ride along either way."""
    B, T, H, D = q.shape
    ks, vs = _pool_scales(kv_layer)
    kpool, vpool = kv_layer["k"], kv_layer["v"]
    from ..ops.attention import on_tpu_platform, ragged_gather_attention

    if not on_tpu_platform():
        return ragged_gather_attention(q, kpool, vpool, tables, positions,
                                       ks, vs)
    from ..ops.pallas.ragged_paged_attention import (
        ragged_paged_attention as kern,
    )

    L = tables.shape[1] * block_size
    qf = q.reshape(B * T, H, D)
    tf = jnp.repeat(tables, T, axis=0) if T > 1 else tables
    lf = jnp.clip(positions + 1, 1, L).reshape(B * T)
    o = _pool_kernel_call(kern, shardings, qf, kpool, vpool, tf, lf, ks, vs)
    return o.reshape(B, T, H, D)


def make_prefill_cont(cfg: LlamaConfig, block_size: int, blocks_per_seq: int,
                      bucket: int, start_blocks: int = 0,
                      shardings: Optional[EngineShardings] = None,
                      kv_quant: bool = False, ragged: bool = False):
    """Compile a CONTINUATION prefill chunk: ``cont(params, kv, ids, n_text,
    block_tables) -> (kv, next_logits)``.

    Prompts longer than the largest prefill bucket process in bucket-sized
    chunks, one per engine step — this executable handles the chunk whose
    first token sits at the STATIC position ``start_blocks * block_size``.
    The chunk's queries attend (a) the ``start`` tokens already written to
    the pool (gathered densely through the block table — amortized over the
    whole chunk, unlike decode's per-token gather) and (b) the chunk itself,
    causally. Keys are the exact concatenation [prior, chunk], so the causal
    offset ``S - T == start`` is exact and the flash kernel stays eligible
    (``kv_lengths = start + n_text`` masks chunk padding; a padded tail also
    writes into null block 0 like every other prefill).

    One executable per chunk start (``max_model_len / bucket - 1`` of them)
    — the static-shape ladder the reference bakes at compile time with its
    ``context_encoding_buckets`` (``cova/mllama-32-11b-vllm-trn1-config.yaml:10-16``),
    extended past the largest bucket. This is what makes a 128k
    ``max_model_len`` practical rather than a config key.

    Cross-attention (mllama) configs chunk too: the gated cross layers
    attend the request's static vision states each chunk (no pool traffic,
    same as ``make_prefill``); the signature gains the
    ``(cross_kv, has_image, cross_len)`` tail.

    ``ragged`` (``SHAI_RAGGED_ATTENTION``): the chunk start becomes DATA —
    ``cont(params, kv, ids, n_text, block_tables, start)`` — and the
    chunk's queries attend their prior context *through the pool* via the
    ragged path (per-query lengths) instead of a static-offset dense
    gather. ONE executable per chunk bucket replaces the whole
    one-per-start continuation ladder, killing the pad waste of
    intermediate chunks compiled for the largest start. Text engines only
    (the ragged gate excludes cross configs).

    ``kv_quant``: int8 pool — the prior-context gather dequantizes, the
    chunk scatter quantizes per block x head (``_scatter_blocks``).
    """
    assert bucket % block_size == 0
    assert ragged or start_blocks >= 1
    start = start_blocks * block_size
    c_blocks = bucket // block_size
    assert ragged or start_blocks + c_blocks <= blocks_per_seq
    cross_set = set(cfg.cross_attention_layers)
    assert not (ragged and cross_set), \
        "ragged continuation serves text engines (the engine gate)"

    def _ragged_impl(params, kv, ids, n_text, block_tables, start_arr):
        p = params["params"]
        B = ids.shape[0]  # == 1
        x = p["embed"]["embedding"][ids].astype(jnp.bfloat16)
        T = x.shape[1]  # == bucket
        start_arr = start_arr.astype(jnp.int32)
        positions = start_arr[:, None] + jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32), (B, T))
        sb = start_arr // block_size                        # [B]
        tbl_chunk = jnp.take_along_axis(
            block_tables,
            sb[:, None] + jnp.arange(c_blocks, dtype=jnp.int32)[None, :],
            axis=1)                                         # [B, c_blocks]
        tables = block_tables[:, :blocks_per_seq]
        pi = 0
        for li in range(cfg.n_layers):
            lp = p[f"layer_{li}"]
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
            q, k, v = _qkv(lp, h, positions, cfg)
            # scatter the chunk FIRST: its queries then attend their own
            # freshly-written keys through the pool, exactly like decode —
            # [prior, chunk] is the pool's table order, no concat needed
            kv[pi] = _scatter_blocks(
                kv[pi], tbl_chunk,
                k.reshape(B, c_blocks, block_size, cfg.n_kv_heads,
                          cfg.head_dim),
                v.reshape(B, c_blocks, block_size, cfg.n_kv_heads,
                          cfg.head_dim), kv_quant)
            o = _ragged_pool_attention(q, kv[pi], tables, positions,
                                       block_size, shardings)
            x = x + _proj(o.reshape(B, T, -1), lp["attn"]["o"])
            x = x + _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"],
                                      cfg.rms_eps))
            pi += 1
        last = jnp.take_along_axis(x, (n_text - 1).reshape(B, 1, 1), axis=1)
        return kv, _logits(p, last, cfg)[:, 0]  # [B, V]

    if ragged:
        def cont(params, kv, ids, n_text, block_tables, start):
            return _ragged_impl(params, kv, ids, n_text, block_tables,
                                start)

        if shardings is None:
            return jax.jit(cont, donate_argnums=(1,))
        sh, rep = shardings, shardings.rep
        kvsh = sh.kv_pool(cfg.n_layers, quant=kv_quant)
        return jax.jit(cont, donate_argnums=(1,),
                       in_shardings=(sh.params, kvsh, rep, rep, rep, rep),
                       out_shardings=(kvsh, rep))

    def _cont_impl(params, kv, ids, n_text, block_tables, cross_kv=None,
                   has_image=None, cross_len=None):
        p = params["params"]
        B = ids.shape[0]  # == 1
        x = p["embed"]["embedding"][ids].astype(jnp.bfloat16)
        T = x.shape[1]  # == bucket
        n = n_text + start  # total valid tokens after this chunk
        positions = start + jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32), (B, T))
        tbl_prior = block_tables[:, :start_blocks]        # [B, start_blocks]
        goff = (tbl_prior[:, :, None] * block_size
                + jnp.arange(block_size)[None, None, :]).reshape(B, start)
        tbl_chunk = block_tables[:, start_blocks:start_blocks + c_blocks]
        ci = 0
        pi = 0  # pool index: cross layers own no KV pool entries
        for li in range(cfg.n_layers):
            lp = p[f"layer_{li}"]
            if li in cross_set:
                x = _cross_layer(lp, x, cross_kv[ci]["k"], cross_kv[ci]["v"],
                                 has_image, cfg, cross_len=cross_len,
                                 shardings=shardings)
                ci += 1
                continue
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
            q, k, v = _qkv(lp, h, positions, cfg)
            if kv_quant:
                # int8 prior context: block-shaped gather so the
                # per-(block, head) scales broadcast on the dequant
                from ..ops.quant import dequantize_kv_blocks

                kprior = dequantize_kv_blocks(
                    kv[pi]["k"][tbl_prior], kv[pi]["ks"][tbl_prior],
                    q.dtype).reshape(B, start, cfg.n_kv_heads, cfg.head_dim)
                vprior = dequantize_kv_blocks(
                    kv[pi]["v"][tbl_prior], kv[pi]["vs"][tbl_prior],
                    q.dtype).reshape(B, start, cfg.n_kv_heads, cfg.head_dim)
            else:
                kflat = kv[pi]["k"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                vflat = kv[pi]["v"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                kprior = kflat[goff].astype(q.dtype)
                vprior = vflat[goff].astype(q.dtype)
            kcat = jnp.concatenate([kprior, k], axis=1)  # [B, start+T, ...]
            vcat = jnp.concatenate([vprior, v], axis=1)
            o = _tp_attention(shardings, q, kcat, vcat, kv_lengths=n,
                              causal=True)
            x = x + _proj(o.reshape(B, T, -1), lp["attn"]["o"])
            x = x + _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"], cfg.rms_eps))
            kv[pi] = _scatter_blocks(
                kv[pi], tbl_chunk,
                k.reshape(B, c_blocks, block_size, cfg.n_kv_heads,
                          cfg.head_dim),
                v.reshape(B, c_blocks, block_size, cfg.n_kv_heads,
                          cfg.head_dim), kv_quant)
            pi += 1
        last = jnp.take_along_axis(x, (n_text - 1).reshape(B, 1, 1), axis=1)
        return kv, _logits(p, last, cfg)[:, 0]  # [B, V]

    if cross_set:
        def cont(params, kv, ids, n_text, block_tables, cross_kv, has_image,
                 cross_len):
            return _cont_impl(params, kv, ids, n_text, block_tables,
                              cross_kv=cross_kv, has_image=has_image,
                              cross_len=cross_len)
    else:
        def cont(params, kv, ids, n_text, block_tables):
            return _cont_impl(params, kv, ids, n_text, block_tables)

    if shardings is None:
        return jax.jit(cont, donate_argnums=(1,))
    sh, rep = shardings, shardings.rep
    kvsh = sh.kv_pool(cfg.n_layers - len(cross_set), quant=kv_quant)
    in_sh = [sh.params, kvsh, rep, rep, rep]
    if cross_set:
        in_sh += [sh.cross_pool(len(cross_set)), rep, rep]
    return jax.jit(cont, donate_argnums=(1,),
                   in_shardings=tuple(in_sh), out_shardings=(kvsh, rep))


def _resolve_paged(paged):
    """Default the paged-kernel switch: on for TPU backends, off elsewhere
    (the interpreter is test-only); the ``SHAI_PAGED_DECODE`` env var (0/1)
    overrides."""
    from ..obs.util import env_flag

    if paged is not None:
        return paged
    env = env_flag("SHAI_PAGED_DECODE", None)
    if env is not None:
        return env
    from ..ops.attention import on_tpu_platform

    return on_tpu_platform()


def _make_token_forward(cfg: LlamaConfig, block_size: int, m_ctx: int,
                        max_num_seqs: int, T: int,
                        shardings: Optional[EngineShardings], paged: bool,
                        ragged: bool = False, kv_quant: bool = False):
    """THE paged-engine forward for ``T`` new tokens per sequence — decode
    is its ``T=1`` instantiation, speculative verify its ``T=k+1``, so the
    two dispatch paths share one layer stack and cannot drift apart (the
    greedy-equivalence invariant rests on this).

    ``fwd(params, kv, tokens [B, T], positions [B, T], tables [B, >=m_ctx]
    [, cross tail]) -> (kv, logits [B, T, V])``: scatters all ``T`` tokens'
    kv into the pool — positions past the context window or a slot's
    reservation route to the null block, the harmless-garbage padding
    convention — then every query attends its own causal window: through
    the Pallas paged kernel with the ``T`` queries flattened into the batch
    axis (the ragged multi-token layout of "Ragged Paged Attention"; the
    one-query-per-row kernel is unchanged), or the dense gather + mask path
    off-TPU.
    """
    L = block_size * m_ctx
    cross_set = set(cfg.cross_attention_layers)

    def paged_attn(qf, kpool, vpool, tablesf, lengthsf, ks=None, vs=None):
        """qf [rows, H, D] over the pool, through the shared
        ``_pool_kernel_call`` dispatch seam (head-split shard_map under
        TP). ``ragged`` swaps in the ragged kernel — same layout, per-row
        compute skip instead of a caller-side context bucket; ``ks``/``vs``
        are an int8 pool's per-(block, head) scales, dequantized in-kernel
        by both."""
        if ragged:
            from ..ops.pallas.ragged_paged_attention import (
                ragged_paged_attention as kernel,
            )
        else:
            from ..ops.pallas.paged_attention import (
                paged_decode_attention as kernel,
            )

        return _pool_kernel_call(kernel, shardings, qf, kpool, vpool,
                                 tablesf, lengthsf, ks, vs)

    def fwd(params, kv, tokens, positions, tables, cross_kv=None,
            has_image=None, slot_idx=None, cross_len=None):
        p = params["params"]
        B = max_num_seqs
        tables = tables[:, :m_ctx]
        x = p["embed"]["embedding"][tokens].astype(jnp.bfloat16)  # [B,T,d]
        # flat write offsets for the T new tokens' kv: [B, T]
        pblk = positions // block_size
        blk = jnp.where(
            pblk < m_ctx,
            jnp.take_along_axis(tables, jnp.clip(pblk, 0, m_ctx - 1),
                                axis=1),
            0)
        widx = blk * block_size + positions % block_size
        if not paged and not kv_quant:
            # flat gather offsets for the whole context window: [B, L]
            goff = (tables[:, :, None] * block_size
                    + jnp.arange(block_size)[None, None, :]).reshape(B, L)
            # query t attends exactly positions <= positions[b, t] (its own
            # just-written token included); padding rows see one dummy token
            mask = (jnp.arange(L)[None, None, :]
                    <= positions[:, :, None])[:, None]  # [B, 1, T, L]
        ci = 0
        pi = 0  # pool index: cross layers own no KV pool entries
        for li in range(cfg.n_layers):
            lp = p[f"layer_{li}"]
            if li in cross_set:
                # slot_idx maps the COMPACTED batch row back to its slot's
                # rows in the full cross-kv buffers (gather fuses into the
                # attention read)
                ck = cross_kv[ci]["k"][slot_idx]
                cv = cross_kv[ci]["v"][slot_idx]
                x = _cross_layer(lp, x, ck, cv, has_image, cfg,
                                 cross_len=cross_len, shardings=shardings)
                ci += 1
                continue
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
            q, kk, vv = _qkv(lp, h, positions, cfg)
            if kv_quant:
                # int8 pool: one read-modify-write requantize per new token
                # (T is 1 for decode, k+1 for verify — a tiny unroll); the
                # block's scale only ever grows, so resident tokens stay
                # within half a step of the final scale
                from ..ops.quant import requantize_block_tokens

                kpool, vpool = kv[pi]["k"], kv[pi]["v"]
                ks, vs = kv[pi]["ks"], kv[pi]["vs"]
                for t in range(T):
                    bt = blk[:, t]
                    pin = positions[:, t] % block_size
                    kq, ksn = requantize_block_tokens(
                        kpool[bt], ks[bt], kk[:, t], pin)
                    vq, vsn = requantize_block_tokens(
                        vpool[bt], vs[bt], vv[:, t], pin)
                    kpool = kpool.at[bt].set(kq)
                    vpool = vpool.at[bt].set(vq)
                    ks = ks.at[bt].set(ksn)
                    vs = vs.at[bt].set(vsn)
                kv[pi] = {"k": kpool, "v": vpool, "ks": ks, "vs": vs}
            else:
                pool_shape = kv[pi]["k"].shape
                kflat = kv[pi]["k"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                vflat = kv[pi]["v"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                kflat = kflat.at[widx].set(kk.astype(kflat.dtype))
                vflat = vflat.at[widx].set(vv.astype(vflat.dtype))
                kv[pi] = {"k": kflat.reshape(pool_shape),
                          "v": vflat.reshape(pool_shape)}
            ksc, vsc = _pool_scales(kv[pi])
            if paged:
                o = paged_attn(
                    q.reshape(B * T, cfg.n_heads, cfg.head_dim),
                    kv[pi]["k"], kv[pi]["v"],
                    jnp.repeat(tables, T, axis=0) if T > 1 else tables,
                    jnp.clip(positions + 1, 1, L).reshape(B * T),
                    ksc, vsc)
                o = o.reshape(B, T, cfg.n_heads, cfg.head_dim)
            elif kv_quant:
                # deviceless int8 path: the gather reference dequantizes
                # right after the block gather (ops.attention)
                from ..ops.attention import ragged_gather_attention

                o = ragged_gather_attention(q, kv[pi]["k"], kv[pi]["v"],
                                            tables, positions, ksc, vsc)
            else:
                kflat = kv[pi]["k"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                vflat = kv[pi]["v"].reshape(-1, cfg.n_kv_heads, cfg.head_dim)
                kctx = kflat[goff]  # [B, L, Hkv, Dh]
                vctx = vflat[goff]
                o = dot_product_attention(q, kctx, vctx, mask=mask)
            pi += 1
            x = x + _proj(o.reshape(B, T, -1), lp["attn"]["o"])
            x = x + _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"],
                                      cfg.rms_eps))
        return kv, _logits(p, x, cfg)  # [B, T, V] f32

    return fwd


def make_decode(cfg: LlamaConfig, block_size: int, blocks_per_seq: int,
                max_num_seqs: int, ctx_blocks: Optional[int] = None,
                shardings: Optional[EngineShardings] = None,
                paged: Optional[bool] = None, feedback: bool = False,
                ragged: bool = False, kv_quant: bool = False):
    """Compile one decode step for the whole slot batch.

    ``decode(params, kv, tokens [B], pos [B], tables [B, M], active [B],
    rng, temperature [B], top_k [B], top_p [B]) -> (kv, next_tokens [B])``.

    ``feedback``: the async-pipeline variant (``SHAI_ASYNC_DECODE``). The
    executable additionally returns ``pos + 1`` so the engine can feed the
    sampled-token and position arrays of step N straight back as step
    N+1's inputs without a host round-trip, and ``pos`` is donated along
    with the KV pool (the position buffer ping-pongs in place; ``tokens``
    is NOT donated — the host still reads step N's sampled tokens back one
    step later for EOS/stop bookkeeping, and a donated buffer could not be
    fetched after being consumed by the next dispatch).

    ``pos[b]`` is the index the new token is written at (== tokens so far).
    Inactive slots carry ``tables`` of zeros and write harmlessly into the
    reserved null block 0.

    ``ctx_blocks`` bounds the attention window to the first ``ctx_blocks``
    table entries — the engine compiles one executable per context bucket
    (``token_generation_buckets``) and dispatches on the longest running
    sequence, so decode cost scales with the bucketed context actually in
    use, not ``max_model_len`` (the reference's token-bucketing,
    ``cova/mllama-32-11b-vllm-trn1-config.yaml:10-16``).

    ``max_num_seqs`` here is the BATCH BUCKET of this executable, not
    necessarily the engine's slot count: the engine compacts active slots
    and dispatches the smallest power-of-two batch covering them, so decode
    cost also scales with occupancy (VERDICT r2 weak #3: a lone sequence no
    longer pays for a full idle batch).

    ``paged``: attention streams straight out of the block pool via the
    Pallas paged kernel (``ops.pallas.paged_attention``) instead of the
    dense ``[B, L, Hkv, Dh]`` gather (VERDICT r2 missing #3). Default: on
    for TPU backends, off elsewhere (the interpreter is test-only); the
    ``SHAI_PAGED_DECODE`` env var (0/1) overrides.

    ``ragged``: one dispatch for mixed context lengths
    (``SHAI_RAGGED_ATTENTION``) — the attention window is the FULL
    ``blocks_per_seq`` table, per-row cost following each row's own
    length (compute skip + fetch elision in
    ``ops.pallas.ragged_paged_attention``), so the engine compiles ONE
    context entry instead of the ``token_generation_buckets`` ladder and
    never dispatches on the longest sequence's bucket.

    ``kv_quant``: int8 KV pool (``SHAI_KV_QUANT=int8``) — writes quantize
    per block x kv-head, reads dequantize in-kernel; the kv pytree carries
    ``ks``/``vs`` scale arrays next to the block pools.

    The layer stack itself is ``_make_token_forward`` at ``T=1`` — shared
    verbatim with the speculative verify executable.
    """
    m_ctx = blocks_per_seq if ctx_blocks is None else ctx_blocks
    assert 1 <= m_ctx <= blocks_per_seq
    assert not ragged or m_ctx == blocks_per_seq, \
        "ragged decode owns the full window; the bucket ladder is gone"
    paged = _resolve_paged(paged)
    cross_set = set(cfg.cross_attention_layers)
    fwd = _make_token_forward(cfg, block_size, m_ctx, max_num_seqs, 1,
                              shardings, paged, ragged=ragged,
                              kv_quant=kv_quant)

    def _decode_impl(params, kv, tokens, pos, tables, active, rng,
                     temperature, top_k, top_p, cross_kv=None, has_image=None,
                     slot_idx=None, cross_len=None):
        kv, logits = fwd(params, kv, tokens[:, None], pos[:, None], tables,
                         cross_kv=cross_kv, has_image=has_image,
                         slot_idx=slot_idx, cross_len=cross_len)
        logits = logits[:, 0]  # [B, V]
        nxt = sample_logits(logits, rng, temperature, top_k, top_p)
        # logprob data rides along (tiny vs the matmuls); the engine only
        # transfers it to the host when a running request asked for it
        top_ids, top_lp, tok_lp = token_logprobs(logits, nxt)
        if feedback:
            return kv, nxt, pos + 1, top_ids, top_lp, tok_lp
        return kv, nxt, top_ids, top_lp, tok_lp

    if cross_set:
        def decode(params, kv, tokens, pos, tables, active, rng,
                   temperature, top_k, top_p, cross_kv, has_image, slot_idx,
                   cross_len):
            return _decode_impl(params, kv, tokens, pos, tables, active, rng,
                                temperature, top_k, top_p,
                                cross_kv=cross_kv, has_image=has_image,
                                slot_idx=slot_idx, cross_len=cross_len)
    else:
        def decode(params, kv, tokens, pos, tables, active, rng,
                   temperature, top_k, top_p):
            return _decode_impl(params, kv, tokens, pos, tables, active, rng,
                                temperature, top_k, top_p)

    donate = (1, 3) if feedback else (1,)
    if shardings is None:
        return jax.jit(decode, donate_argnums=donate)
    sh, rep = shardings, shardings.rep
    kvsh = sh.kv_pool(cfg.n_layers - len(cross_set), quant=kv_quant)
    in_sh = (sh.params, kvsh) + (rep,) * 8
    if cross_set:
        in_sh += (sh.cross_pool(len(cross_set)), rep, rep, rep)
    out_sh = (kvsh,) + (rep,) * (5 if feedback else 4)
    return jax.jit(decode, donate_argnums=donate,
                   in_shardings=in_sh, out_shardings=out_sh)


def make_verify(cfg: LlamaConfig, block_size: int, blocks_per_seq: int,
                max_num_seqs: int, k: int, ctx_blocks: Optional[int] = None,
                shardings: Optional[EngineShardings] = None,
                paged: Optional[bool] = None, ragged: bool = False,
                kv_quant: bool = False):
    """Compile one speculative VERIFY step: score ``k + 1`` positions per
    sequence in ONE paged-attention dispatch.

    ``verify(params, kv, tokens [B, k+1], pos0 [B], tables [B, M],
    active [B], rng, temperature [B], top_k [B], top_p [B]) ->
    (kv, o [B, k+1], oex [B, k], accept_p [B, k], o_lp [B, k+1],
    d_lp [B, k], oex_lp [B, k], top_ids [B, k+1, K], top_lp [B, k+1, K])``.

    ``tokens[:, 0]`` is each slot's pending token, ``tokens[:, 1:]`` the
    drafted continuation (zero-padded past the slot's true draft length —
    padded positions write into the null block / reserved tail and their
    outputs are never committed). ``pos0[b]`` is the cache index the
    pending token is written at; position ``i`` lands at ``pos0 + i``. The
    layer stack is ``_make_token_forward`` at ``T=k+1`` — shared verbatim
    with vanilla decode.

    Outputs, per position ``i`` (predicting the token at ``pos0 + i + 1``):
    ``o`` a sample from the full target distribution (argmax at temperature
    0), ``oex`` a sample with the draft token removed AFTER the top-k/top-p
    masks (the rejection-resample stays inside vanilla's support —
    ``ops.sampling.sample_excluding``), ``accept_p`` the draft token's
    probability under the ACTUAL sampling distribution
    (``ops.sampling.sampling_probs``), plus raw logprob data for every
    token the engine might commit (the OpenAI ``logprobs`` surface):
    ``o_lp``/``d_lp``/``oex_lp`` and the top-K alternatives. Acceptance
    itself is a host-side walk (``speculative.accept_drafts``) — per-slot
    draft lengths are dynamic, the executable stays static-shaped.
    """
    assert k >= 1
    m_ctx = blocks_per_seq if ctx_blocks is None else ctx_blocks
    assert 1 <= m_ctx <= blocks_per_seq
    assert not ragged or m_ctx == blocks_per_seq, \
        "ragged verify owns the full window; the bucket ladder is gone"
    T = k + 1
    paged = _resolve_paged(paged)
    cross_set = set(cfg.cross_attention_layers)
    fwd = _make_token_forward(cfg, block_size, m_ctx, max_num_seqs, T,
                              shardings, paged, ragged=ragged,
                              kv_quant=kv_quant)

    def _verify_impl(params, kv, tokens, pos0, tables, active, rng,
                     temperature, top_k, top_p, cross_kv=None, has_image=None,
                     slot_idx=None, cross_len=None):
        B = max_num_seqs
        positions = pos0[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        kv, logits = fwd(params, kv, tokens, positions, tables,
                         cross_kv=cross_kv, has_image=has_image,
                         slot_idx=slot_idx, cross_len=cross_len)
        draft = tokens[:, 1:]  # [B, k]
        bt = jnp.broadcast_to(temperature[:, None], (B, T))
        bk = jnp.broadcast_to(top_k[:, None], (B, T))
        bp = jnp.broadcast_to(top_p[:, None], (B, T))
        # independent per-position samples: one folded key each — categorical
        # over a [B, T, V] batch already draws per-row
        o_tok = sample_logits(logits, jax.random.fold_in(rng, 1),
                              bt, bk, bp)
        # rejection resample: the draft token is removed AFTER the
        # top-k/top-p masks, keeping the resample inside vanilla's support
        oex = sample_excluding(logits[:, :k], jax.random.fold_in(rng, 2),
                               draft, bt[:, :k], bk[:, :k], bp[:, :k])
        accept_p = jnp.take_along_axis(
            sampling_probs(logits[:, :k], bt[:, :k], bk[:, :k], bp[:, :k]),
            draft[..., None], axis=-1)[..., 0]
        # raw (pre-temperature) logprob surface for every committable token
        logp = logits - jax.scipy.special.logsumexp(logits, axis=-1,
                                                    keepdims=True)
        top_lp, top_ids = jax.lax.top_k(logp, K_LOGPROBS)
        o_lp = jnp.take_along_axis(logp, o_tok[..., None], axis=-1)[..., 0]
        d_lp = jnp.take_along_axis(logp[:, :k], draft[..., None],
                                   axis=-1)[..., 0]
        oex_lp = jnp.take_along_axis(logp[:, :k], oex[..., None],
                                     axis=-1)[..., 0]
        return (kv, o_tok, oex, accept_p, o_lp, d_lp, oex_lp,
                top_ids.astype(jnp.int32), top_lp)

    if cross_set:
        def verify(params, kv, tokens, pos0, tables, active, rng,
                   temperature, top_k, top_p, cross_kv, has_image, slot_idx,
                   cross_len):
            return _verify_impl(params, kv, tokens, pos0, tables, active,
                                rng, temperature, top_k, top_p,
                                cross_kv=cross_kv, has_image=has_image,
                                slot_idx=slot_idx, cross_len=cross_len)
    else:
        def verify(params, kv, tokens, pos0, tables, active, rng,
                   temperature, top_k, top_p):
            return _verify_impl(params, kv, tokens, pos0, tables, active,
                                rng, temperature, top_k, top_p)

    if shardings is None:
        return jax.jit(verify, donate_argnums=(1,))
    sh, rep = shardings, shardings.rep
    kvsh = sh.kv_pool(cfg.n_layers - len(cross_set), quant=kv_quant)
    in_sh = (sh.params, kvsh) + (rep,) * 8
    if cross_set:
        in_sh += (sh.cross_pool(len(cross_set)), rep, rep, rep)
    return jax.jit(verify, donate_argnums=(1,),
                   in_shardings=in_sh,
                   out_shardings=(kvsh,) + (rep,) * 8)


def make_fused_step(cfg: LlamaConfig, block_size: int, blocks_per_seq: int,
                    max_num_seqs: int, bucket: int,
                    shardings: Optional[EngineShardings] = None,
                    paged: Optional[bool] = None, feedback: bool = False,
                    kv_quant: bool = False):
    """Compile ONE mixed-phase ragged engine step (``SHAI_FUSED_STEP``):
    the whole decode batch PLUS one chunked-prefill continuation window in
    a single dispatch.

    ``fused(params, kv, tokens [B], pos [B], tables [B, M], active [B],
    rng, temperature [B], top_k [B], top_p [B], c_ids [1, C],
    c_ntext [1], c_table [1, M], c_start [1]) ->
    (kv, next_tokens [B][, pos + 1], top_ids, top_lp, tok_lp,
    c_logits [1, V])``.

    Two sections share one layer walk over one donated pool:

    - the DECODE section is ``make_decode``'s math verbatim — the ``T=1``
      ``_make_token_forward`` body (same write offsets, same int8
      read-modify-write requantize) with on-device sampling + logprobs —
      so fused-off/fused-on token-exactness reduces to the section
      ordering argument below;
    - the CHUNK section is the ragged continuation's math verbatim
      (``make_prefill_cont(ragged=True)``): dynamic ``c_start``, chunk
      scatter first, queries attending their prior context through the
      pool. Its ``c_logits`` come back RAW — the host samples with the
      group-specific rng fold, exactly as the laddered path does. A step
      with no chunk passes null args (zero ids/table, ``c_ntext=1``,
      ``c_start=0``): the window writes into the reserved null block 0
      and its logits are dropped, the harmless-garbage padding
      convention.

    Exactness vs the laddered oracle hangs on per-layer ordering: the
    chunk scatters BEFORE the decode rows write, matching the oracle's
    device order (the continuation dispatch completes before the decode
    dispatch it precedes), so any write collision through a stale table
    resolves identically. Decode queries then read the chunk's
    layer-``l`` keys like the oracle's decode step reads the finished
    continuation's; the chunk's queries never read this step's decode
    writes (decode rows write past their own prompts into blocks the
    chunk's ``length``-bounded reads cannot reach — block tables only
    ever share REGISTERED full prefix blocks, and the null block 0 sits
    outside every live window).

    On TPU both sections' queries flatten into ONE ragged kernel call
    (``ops.attention.mixed_phase_ragged_attention`` — ``B + C``
    single-query rows, the kernel blind to phase). Off-TPU each section
    keeps its own oracle's attention function (dense gather + mask or the
    int8 gather reference for decode, ``ragged_gather_attention`` for the
    chunk) because the two reference softmaxes need not be bitwise
    interchangeable.

    One executable per BATCH BUCKET replaces the decode context ladder ×
    batch ladder, the per-bucket ragged continuation ladder, and the
    cached-admission entries: the chunk window ``C`` is pinned to the
    largest prefill bucket. Text engines only (the ragged gate excludes
    cross configs); ragged owns the full ``blocks_per_seq`` window.
    """
    assert bucket % block_size == 0
    assert not cfg.cross_attention_layers, \
        "fused step serves text engines (the ragged gate)"
    m_ctx = blocks_per_seq
    c_blocks = bucket // block_size
    L = block_size * m_ctx
    paged = _resolve_paged(paged)

    def _pool_call(qf, kpool, vpool, tf, lf, ks, vs):
        from ..ops.pallas.ragged_paged_attention import (
            ragged_paged_attention as kernel,
        )

        return _pool_kernel_call(kernel, shardings, qf, kpool, vpool, tf,
                                 lf, ks, vs)

    def _fused_impl(params, kv, tokens, pos, tables, active, rng,
                    temperature, top_k, top_p, c_ids, c_ntext, c_table,
                    c_start):
        from ..ops.attention import mixed_phase_ragged_attention

        p = params["params"]
        B = max_num_seqs
        C = bucket
        Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
        tables = tables[:, :m_ctx]
        # -- decode section inputs: make_decode verbatim (T == 1) --------
        x = p["embed"]["embedding"][tokens[:, None]].astype(jnp.bfloat16)
        positions = pos[:, None]                                # [B, 1]
        pblk = positions // block_size
        blk = jnp.where(
            pblk < m_ctx,
            jnp.take_along_axis(tables, jnp.clip(pblk, 0, m_ctx - 1),
                                axis=1),
            0)
        widx = blk * block_size + positions % block_size
        if not paged and not kv_quant:
            goff = (tables[:, :, None] * block_size
                    + jnp.arange(block_size)[None, None, :]).reshape(B, L)
            mask = (jnp.arange(L)[None, None, :]
                    <= positions[:, :, None])[:, None]  # [B, 1, 1, L]
        # -- chunk section inputs: the ragged continuation verbatim ------
        xc = p["embed"]["embedding"][c_ids].astype(jnp.bfloat16)
        c_start32 = c_start.astype(jnp.int32)
        c_positions = c_start32[:, None] + jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32), (1, C))
        sb = c_start32 // block_size
        tbl_chunk = jnp.take_along_axis(
            c_table,
            sb[:, None] + jnp.arange(c_blocks, dtype=jnp.int32)[None, :],
            axis=1)                                      # [1, c_blocks]
        c_tables = c_table[:, :m_ctx]
        for li in range(cfg.n_layers):
            lp = p[f"layer_{li}"]
            # chunk scatter FIRST each layer: the oracle's continuation
            # dispatch finishes before its decode dispatch, so stale-table
            # write collisions must resolve in the same order here
            hc = _rmsnorm(xc, lp["attn_norm"]["scale"], cfg.rms_eps)
            qc, kc, vc = _qkv(lp, hc, c_positions, cfg)
            kv[li] = _scatter_blocks(
                kv[li], tbl_chunk,
                kc.reshape(1, c_blocks, block_size, Hkv, Dh),
                vc.reshape(1, c_blocks, block_size, Hkv, Dh), kv_quant)
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.rms_eps)
            q, kk, vv = _qkv(lp, h, positions, cfg)
            if kv_quant:
                from ..ops.quant import requantize_block_tokens

                kpool, vpool = kv[li]["k"], kv[li]["v"]
                ks, vs = kv[li]["ks"], kv[li]["vs"]
                bt = blk[:, 0]
                pin = positions[:, 0] % block_size
                kq, ksn = requantize_block_tokens(
                    kpool[bt], ks[bt], kk[:, 0], pin)
                vq, vsn = requantize_block_tokens(
                    vpool[bt], vs[bt], vv[:, 0], pin)
                kv[li] = {"k": kpool.at[bt].set(kq),
                          "v": vpool.at[bt].set(vq),
                          "ks": ks.at[bt].set(ksn),
                          "vs": vs.at[bt].set(vsn)}
            else:
                pool_shape = kv[li]["k"].shape
                kflat = kv[li]["k"].reshape(-1, Hkv, Dh)
                vflat = kv[li]["v"].reshape(-1, Hkv, Dh)
                kflat = kflat.at[widx].set(kk.astype(kflat.dtype))
                vflat = vflat.at[widx].set(vv.astype(vflat.dtype))
                kv[li] = {"k": kflat.reshape(pool_shape),
                          "v": vflat.reshape(pool_shape)}
            ksc, vsc = _pool_scales(kv[li])
            if paged:
                o_dec, o_chk = mixed_phase_ragged_attention(
                    q.reshape(B, cfg.n_heads, Dh),
                    qc.reshape(C, cfg.n_heads, Dh),
                    kv[li]["k"], kv[li]["v"], tables, c_tables,
                    pos, c_positions.reshape(C), ksc, vsc,
                    pool_call=_pool_call)
                o = o_dec.reshape(B, 1, cfg.n_heads, Dh)
                oc = o_chk.reshape(1, C, cfg.n_heads, Dh)
            else:
                # off-TPU each section keeps ITS OWN oracle's attention
                # function — the two reference softmaxes need not match
                # bitwise, and token-exactness is per-section
                if kv_quant:
                    from ..ops.attention import ragged_gather_attention

                    o = ragged_gather_attention(
                        q, kv[li]["k"], kv[li]["v"], tables, positions,
                        ksc, vsc)
                else:
                    kflat = kv[li]["k"].reshape(-1, Hkv, Dh)
                    vflat = kv[li]["v"].reshape(-1, Hkv, Dh)
                    o = dot_product_attention(q, kflat[goff], vflat[goff],
                                              mask=mask)
                oc = _ragged_pool_attention(qc, kv[li], c_tables,
                                            c_positions, block_size,
                                            shardings)
            x = x + _proj(o.reshape(B, 1, -1), lp["attn"]["o"])
            x = x + _mlp(lp, _rmsnorm(x, lp["mlp_norm"]["scale"],
                                      cfg.rms_eps))
            xc = xc + _proj(oc.reshape(1, C, -1), lp["attn"]["o"])
            xc = xc + _mlp(lp, _rmsnorm(xc, lp["mlp_norm"]["scale"],
                                        cfg.rms_eps))
        logits = _logits(p, x, cfg)[:, 0]                       # [B, V]
        nxt = sample_logits(logits, rng, temperature, top_k, top_p)
        top_ids, top_lp, tok_lp = token_logprobs(logits, nxt)
        lastc = jnp.take_along_axis(xc, (c_ntext - 1).reshape(1, 1, 1),
                                    axis=1)
        c_logits = _logits(p, lastc, cfg)[:, 0]                 # [1, V]
        if feedback:
            return kv, nxt, pos + 1, top_ids, top_lp, tok_lp, c_logits
        return kv, nxt, top_ids, top_lp, tok_lp, c_logits

    def fused(params, kv, tokens, pos, tables, active, rng, temperature,
              top_k, top_p, c_ids, c_ntext, c_table, c_start):
        return _fused_impl(params, kv, tokens, pos, tables, active, rng,
                           temperature, top_k, top_p, c_ids, c_ntext,
                           c_table, c_start)

    donate = (1, 3) if feedback else (1,)
    if shardings is None:
        return jax.jit(fused, donate_argnums=donate)
    sh, rep = shardings, shardings.rep
    kvsh = sh.kv_pool(cfg.n_layers, quant=kv_quant)
    in_sh = (sh.params, kvsh) + (rep,) * 12
    out_sh = (kvsh,) + (rep,) * (6 if feedback else 5)
    return jax.jit(fused, donate_argnums=donate,
                   in_shardings=in_sh, out_shardings=out_sh)
