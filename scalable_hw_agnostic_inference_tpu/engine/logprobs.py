"""Per-token logprob capture (the OpenAI `logprobs` field).

Split from engine.py (VERDICT r3 weak #5): the admission ladder stays in
engine.py; this module owns logprob entry construction/recording. Functions take the engine instance
explicitly — they are the same code paths, re-homed.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

def _lp_entry(n_top: int, tok: int, tok_lp, top_ids, top_lp) -> Dict:
    return {"token": int(tok), "logprob": float(tok_lp),
            "top_ids": [int(i) for i in top_ids[:n_top]],
            "top_logprobs": [float(v) for v in top_lp[:n_top]]}

def _record_admission_lps(eng, logits, toks, rows) -> None:
    """Per-token logprobs for freshly sampled first tokens — ``rows``
    maps batch row -> the seated _Running; only called when some row
    asked for logprobs (logits stay on device otherwise)."""
    ids, lps, tok_lp = eng._lp1(logits, jnp.asarray(toks, jnp.int32))
    ids, lps, tok_lp = np.asarray(ids), np.asarray(lps), np.asarray(tok_lp)
    for i, s in rows:
        n_top = s.req.params.logprobs
        if n_top:
            s.lps.append(eng._lp_entry(n_top, toks[i], tok_lp[i],
                                        ids[i], lps[i]))
