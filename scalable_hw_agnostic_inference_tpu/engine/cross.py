"""Mllama cross-attention slot plumbing (vision states <-> cross-kv buffers).

Split from engine.py (VERDICT r3 weak #5): the admission ladder stays in
engine.py; this module owns the per-slot cross-kv buffer writes/reads. Functions take the engine instance
explicitly — they are the same code paths, re-homed.
"""

from __future__ import annotations

import jax.numpy as jnp

from .types import Request

def _set_slot_cross(eng, slot: int, req: Request):
    """Project the request's vision states into the slot's cross-kv
    buffer rows (or gate the slot off for text-only). Returns the
    ``(cross_kv [1, Lv, ...], has_image [1])`` prefill args."""
    Lv = max(eng.cross_seq_len, 1)
    if req.cross_states is None:
        eng._has_image[slot] = 0.0
        eng._cross_len[slot] = Lv
        return (eng._cross_zeros(1), jnp.zeros((1,), jnp.float32),
                jnp.full((1,), Lv, jnp.int32))
    per_layer = eng._cross_embed(eng.params,
                                  jnp.asarray(req.cross_states))
    eng._cross_kv = eng._cross_write(
        eng._cross_kv, per_layer, jnp.int32(slot))
    eng._has_image[slot] = 1.0
    n_valid = req.cross_len or Lv
    eng._cross_len[slot] = n_valid
    # prefill arg dtype must match the warmed signature (buffer dtype)
    dt = eng._cross_kv[0]["k"].dtype
    one = [{"k": c["k"][None].astype(dt), "v": c["v"][None].astype(dt)}
           for c in per_layer]
    return (one, jnp.ones((1,), jnp.float32),
            jnp.full((1,), n_valid, jnp.int32))

def _cross_zeros(eng, K: int):
    """Zero cross-kv prefill args for text-only rows, cached per K."""
    cache = getattr(eng, "_cross_zero_cache", None)
    if cache is None:
        cache = eng._cross_zero_cache = {}
    if K not in cache:
        tmpl = eng._cross_kv[0]["k"]
        shape = (K,) + tmpl.shape[1:]
        cache[K] = [{"k": jnp.zeros(shape, tmpl.dtype),
                     "v": jnp.zeros(shape, tmpl.dtype)}
                    for _ in eng._cross_kv]
    return cache[K]


def _slot_cross_args(eng, slot: int):
    """One-row cross args read back from the slot's buffers (chunk
    continuations on a cross engine)."""
    one = [{"k": buf["k"][slot][None], "v": buf["v"][slot][None]}
           for buf in eng._cross_kv]
    return (one,
            jnp.asarray([eng._has_image[slot]], jnp.float32),
            jnp.asarray([eng._cross_len[slot]], jnp.int32))
