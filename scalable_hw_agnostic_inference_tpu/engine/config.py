"""Engine configuration — the ``vllm_config.yaml`` ConfigMap contract.

The reference mounts a YAML ConfigMap and splats it into ``vllm.LLM(**cfg)``
(reference ``app/vllm_model_api.py:33-34``, knobs at
``cova/mllama-32-11b-vllm-trn1-config.yaml:8-23``). :class:`EngineConfig`
accepts the same key names (vLLM-style) plus TPU-native extras, so existing
deployment YAML carries over unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    model: str = ""                       # HF id or "tiny"
    max_model_len: int = 2048             # max prompt+generation per sequence
    max_num_seqs: int = 8                 # running-batch slots
    block_size: int = 16                  # KV block granularity (tokens)
    num_blocks: int = 0                   # 0 = auto from max_model_len*max_num_seqs
    context_encoding_buckets: Sequence[int] = (128, 512)   # prefill shapes
    # decode attention-window buckets: one decode executable per bucket,
    # dispatched on the longest running sequence (empty = max_model_len only)
    token_generation_buckets: Sequence[int] = ()
    is_continuous_batching: bool = True
    # max same-bucket prompts admitted as ONE batched prefill call (rounded
    # to a power of two per compiled executable); 1 = serial prefill
    max_prefill_batch: int = 4
    tensor_parallel_size: int = 1
    dtype: str = "bfloat16"
    # weight-only quantization: None/"" = bf16 weights, "int8" = per-channel
    # int8 (ops.quant) — the vLLM `quantization:` config key, TPU-natively
    quantization: Optional[str] = None
    # automatic prefix caching (the vLLM knob): shared prompt prefixes reuse
    # KV blocks (refcounted) and skip their prefill compute via the
    # continuation-prefill executables
    enable_prefix_caching: bool = False
    # on-device sampling (reference: global_topk 64, dynamic)
    global_topk: int = 64
    max_new_tokens: int = 128
    seed: int = 0
    # speculative decoding (the vLLM knobs): "[ngram]" enables model-free
    # prompt-lookup drafting; each decode step then verifies up to
    # num_speculative_tokens drafted tokens in ONE multi-position executable
    # (engine/speculative.py). "" = off.
    speculative_model: str = ""
    num_speculative_tokens: int = 0
    # n-gram window the drafter matches against prompt+generated history
    ngram_prompt_lookup_max: int = 4
    ngram_prompt_lookup_min: int = 1
    # conformance observability (obs.slo / obs.sentinel): per-model SLO
    # targets (0 = objective off; SHAI_SLO_* env vars override) and the
    # PERF_MODEL.json projection key the perf sentinel compares live tok/s
    # against ("" = geometry heuristic over the model id)
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    slo_error_rate: float = 0.0
    perf_projection: str = ""
    # disaggregated prefill/decode serving (kvnet/): "prefill" pods finish
    # the prompt, demote its KV to the host tier, and return a handoff
    # instead of decoding; "decode" pods accept handoffs and pull warm KV
    # from the peer; "both" (default) is the monolithic pod. The SHAI_ROLE
    # env knob overrides this config field at boot (kvnet.resolve_role).
    role: str = "both"

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        if self.max_model_len % self.block_size:
            raise ValueError("max_model_len must be a multiple of block_size")
        if not self.context_encoding_buckets:
            raise ValueError("need at least one prefill bucket")
        bad = [b for b in self.context_encoding_buckets if b > self.max_model_len]
        if bad:
            raise ValueError(f"prefill buckets {bad} exceed max_model_len")
        misaligned = [b for b in self.context_encoding_buckets
                      if b % self.block_size]
        if misaligned:
            raise ValueError(
                f"prefill buckets {misaligned} not multiples of "
                f"block_size={self.block_size}")
        # token_generation_buckets get the SAME shape discipline as the
        # prefill buckets: a decode executable compiled past max_model_len
        # (or off block alignment) would warm a window no sequence can
        # reach — or worse, mis-size its block-table slice
        bad = [b for b in self.token_generation_buckets
               if b > self.max_model_len]
        if bad:
            raise ValueError(
                f"token_generation_buckets {bad} exceed max_model_len")
        misaligned = [b for b in self.token_generation_buckets
                      if b < 1 or b % self.block_size]
        if misaligned:
            raise ValueError(
                f"token_generation_buckets {misaligned} not positive "
                f"multiples of block_size={self.block_size}")
        if self.quantization not in (None, "", "int8"):
            raise ValueError(
                f"unsupported quantization {self.quantization!r} "
                f"(supported: int8)")
        if self.speculative_model not in ("", "[ngram]"):
            raise ValueError(
                f"unsupported speculative_model "
                f"{self.speculative_model!r} (supported: \"[ngram]\")")
        if self.num_speculative_tokens < 0:
            raise ValueError("num_speculative_tokens must be >= 0")
        if self.speculative_model and self.num_speculative_tokens:
            if not (1 <= self.ngram_prompt_lookup_min
                    <= self.ngram_prompt_lookup_max):
                raise ValueError(
                    f"need 1 <= ngram_prompt_lookup_min "
                    f"({self.ngram_prompt_lookup_min}) <= "
                    f"ngram_prompt_lookup_max "
                    f"({self.ngram_prompt_lookup_max})")
            if self.num_speculative_tokens >= self.max_model_len:
                raise ValueError(
                    "num_speculative_tokens must be < max_model_len")
        for knob in ("slo_ttft_ms", "slo_tpot_ms", "slo_error_rate"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0 (0 disables)")
        if self.role not in ("prefill", "decode", "both"):
            # the CONFIG field is strict (a deploy manifest typo is a
            # deploy error); the SHAI_ROLE env override stays lenient
            raise ValueError(
                f"unsupported role {self.role!r} "
                f"(supported: prefill, decode, both)")

    @property
    def speculative_enabled(self) -> bool:
        """Speculative decoding is live: both vLLM knobs set (a drafter
        named but k == 0 means vanilla decode, matching vLLM)."""
        return bool(self.speculative_model) and self.num_speculative_tokens > 0

    @property
    def blocks_per_seq(self) -> int:
        return self.max_model_len // self.block_size

    @property
    def total_blocks(self) -> int:
        if self.num_blocks:
            return self.num_blocks
        return self.blocks_per_seq * self.max_num_seqs

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineConfig":
        """Accept vLLM key names; unknown keys are ignored with a record."""
        known = {f.name for f in dataclasses.fields(cls)}
        aliases = {
            "device": None,                 # vLLM "neuron"/"cuda" — meaningless here
            "max_num_batched_tokens": None,  # derived from buckets
            "override_neuron_config": None,
        }
        kwargs, ignored = {}, []
        for k, v in d.items():
            if k in known:
                kwargs[k] = tuple(v) if isinstance(v, list) else v
            elif k in aliases:
                ignored.append(k)
            elif k == "sequence_parallel_enabled":
                ignored.append(k)           # reference sets False explicitly
            else:
                ignored.append(k)
        cfg = cls(**kwargs)
        object.__setattr__(cfg, "_ignored_keys", tuple(ignored))
        return cfg

    @classmethod
    def from_yaml(cls, path: str) -> "EngineConfig":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    @property
    def ignored_keys(self) -> tuple:
        return getattr(self, "_ignored_keys", ())
