"""Continuous-batching engine: slots, scheduler, and the step loop.

Reference behavior being reproduced (via the vLLM neuron fork there):
``is_continuous_batching: True`` with bucketed context encoding and on-device
sampling (``cova/mllama-32-11b-vllm-trn1-config.yaml:10-22``). The TPU shape
of it: a fixed slot batch (``max_num_seqs``) decoded by ONE compiled step,
at most one bucketed prefill admitted per step, paged KV with optimistic
admission and recompute-preemption when the block pool runs dry (vLLM's
recompute policy; the preempted sequence's generated tokens simply become
prompt suffix on re-admission).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.bucketing import BucketRegistry
from ..models.llama import LlamaConfig
from ..obs import sentinel as obs_sentinel
from ..obs.hbm import HbmLedger
from ..obs.slo import SloEngine, SloTargets
from ..obs.steploop import StepTelemetry
from ..obs.trace import annotate
from ..resilience import faults as _faults
from ..resilience import qos as _qos
from ..ops.sampling import sample_logits
from .cache import PagedKVCache
from .config import EngineConfig
from .resident import InflightStep, ResidentBatch, composition_sig
from .runner import make_decode, make_prefill
from .types import (  # noqa: F401  (re-exported: public engine API)
    Finished,
    Request,
    SamplingParams,
    _Running,
)
from . import cross as _cross_mod
from . import logprobs as _lp_mod
from . import warm as _warm_mod

log = logging.getLogger(__name__)


def _resolve_async() -> bool:
    """``SHAI_ASYNC_DECODE`` gate, default ON: pipelined decode with
    device-resident batch state and one-step-lookahead dispatch. ``0`` runs
    the lock-step path — the reference oracle the differential tests
    (``tests/test_engine_async.py``) compare against."""
    from ..obs.util import env_flag

    return env_flag("SHAI_ASYNC_DECODE", True)


class LLMEngine:
    """Drive with :meth:`add_request` + :meth:`step`, or offline
    :meth:`generate`. Single-threaded by design — one engine per pod, the
    serving layer serializes onto the model lane (``serve.app``)."""

    def __init__(self, model_cfg: LlamaConfig, params: Any, ecfg: EngineConfig,
                 mesh=None, cross_seq_len: int = 0):
        self.cfg = model_cfg
        self.ecfg = ecfg
        self.params = params
        # mllama: slot-indexed cross-kv buffers (the encoder cache). Lv is
        # static per checkpoint (tiles x (patches+1)); rows gate off via
        # has_image when a slot holds a text-only request.
        self.cross_seq_len = cross_seq_len
        if model_cfg.cross_attention_layers and not cross_seq_len:
            raise ValueError("mllama config needs cross_seq_len (Lv)")
        # HBM budget gate: on a real device an over-budget geometry must
        # refuse to boot HERE, with the breakdown, instead of OOMing minutes
        # into warmup (VERDICT r3 missing #2). CPU runs (tests, virtual-mesh
        # dryruns) skip unless SHAI_ENFORCE_HBM=1 opts in.
        from ..obs.util import env_flag as _env_flag

        if (jax.devices()[0].platform != "cpu"
                or _env_flag("SHAI_ENFORCE_HBM", False)):
            from ..core.budget import causal_lm_budget, detect_hbm_gib

            causal_lm_budget(
                model_cfg, ecfg, cross_seq_len=cross_seq_len,
                hbm_gib_per_chip=detect_hbm_gib(jax.devices()[0]),
            ).check()
        # tensor parallelism: params arrive sharded (serve layer runs
        # shard_pytree); the pool and both executables follow the same plan
        self.shardings = None
        if mesh is not None and mesh.shape.get("tp", 1) > 1:
            from .runner import EngineShardings

            self.shardings = EngineShardings(mesh, params, model_cfg)
        # cross layers own no pool entries — sizing the pool by self-attn
        # layer count returns ~20% of KV HBM on 11B-Vision to real blocks
        n_pool_layers = (model_cfg.n_layers
                         - len(model_cfg.cross_attention_layers))
        kv_dtype = jnp.bfloat16 if ecfg.dtype == "bfloat16" else jnp.float32
        # int8 KV-block quantization (SHAI_KV_QUANT=int8, default off):
        # the pool holds int8 blocks + per-(block, head) f32 scales — ~2x
        # KV blocks per HBM byte, priced through cache.pool_bytes so the
        # HBM ledger and admission gate see the real capacity. Lenient
        # parse: an unrecognized value warns and stays off (a typo'd
        # quant knob must not crash-loop a serving tier).
        from ..obs.util import env_str as _env_str

        kvq = _env_str("SHAI_KV_QUANT", "").strip().lower()
        if kvq not in ("", "0", "off", "none", "int8"):
            log.warning("SHAI_KV_QUANT=%r not recognized (supported: int8)"
                        " — KV quantization stays off", kvq)
            kvq = ""
        self._kv_quant = kvq == "int8"
        # ragged paged attention (SHAI_RAGGED_ATTENTION, default off):
        # decode/verify attend mixed context lengths in ONE full-window
        # dispatch (per-row compute skip), so the token_generation_buckets
        # ladder collapses to a single context entry and chunked prefill's
        # continuation ladder collapses to one dynamic-start executable
        # per chunk bucket. Text engines only: the ragged continuation
        # does not carry the mllama cross tail.
        self._ragged = bool(_env_flag("SHAI_RAGGED_ATTENTION", False)
                            and not model_cfg.cross_attention_layers)
        # prefix caching serves the plain-text path only: cross models'
        # cache semantics (vision states) don't content-address by tokens
        prefix_caching = (ecfg.enable_prefix_caching
                          and not model_cfg.cross_attention_layers)
        # host KV tier (SHAI_KVTIER, kvtier/): prefix-cache eviction and
        # preemption demote blocks to a bounded host-RAM pool; admission
        # misses fall through to it and swap KV back in instead of
        # re-running prefill. Rides the prefix cache (same chain hashes),
        # unsharded pools only — a TP pool's restore scatter would need
        # per-rank placement the tier does not carry.
        tier = None
        if prefix_caching and self.shardings is None:
            from ..kvtier.pool import maybe_host_tier

            tier = maybe_host_tier(
                n_layers=n_pool_layers, block_size=ecfg.block_size,
                n_kv_heads=model_cfg.n_kv_heads,
                head_dim=model_cfg.head_dim,
                dtype=np.int8 if self._kv_quant else np.dtype(kv_dtype),
                quant=self._kv_quant)
        # disaggregated serving role (kvnet): env wins over ecfg.role. A
        # prefill pod demotes every finished request's full prompt-block
        # run to its host tier (the handoff the decode pod pulls); that
        # needs prefix caching + the tier, so a mis-deployed prefill pod
        # warns loudly and degrades to handing off kv_ready=False.
        from ..kvnet import resolve_role

        self.role = resolve_role(ecfg.role)
        self._prefill_role = self.role == "prefill"
        if self._prefill_role and tier is None:
            log.warning(
                "role=prefill but no host KV tier is configured "
                "(need enable_prefix_caching + SHAI_KVTIER=1, unsharded "
                "pool) — handoffs will advertise kv_ready=false and "
                "decode peers will recompute")
        kv_sharding = None
        if self.shardings is not None:
            kv_sharding = dict(self.shardings.kv_layer)
            if self._kv_quant:
                kv_sharding["ks"] = self.shardings.kv_scale
                kv_sharding["vs"] = self.shardings.kv_scale
        self.cache = PagedKVCache(
            n_pool_layers, model_cfg.n_kv_heads, model_cfg.head_dim,
            ecfg.total_blocks, ecfg.block_size, ecfg.blocks_per_seq,
            dtype=kv_dtype,
            sharding=kv_sharding,
            enable_prefix_caching=prefix_caching,
            tier=tier,
            quant=self._kv_quant,
        )
        self.buckets = BucketRegistry(sorted(ecfg.context_encoding_buckets))
        # chunked-prefill prompt cap: whole bucket-sized chunks only (the
        # continuation ladder is a static set of start offsets), and at
        # least one position left for generation
        C = self.buckets.max
        self._chunk_cap = min(ecfg.max_model_len - 1,
                              (ecfg.max_model_len // C) * C)
        self._prefill = {}
        # decode executables keyed (ctx_bucket, batch_bucket): the attention
        # window is the smallest token_generation_bucket covering the longest
        # running sequence, the batch the smallest power of two covering the
        # active slots — decode cost tracks context AND occupancy in use
        bs = ecfg.block_size
        tg = [min(-(-t // bs), ecfg.blocks_per_seq)
              for t in ecfg.token_generation_buckets]
        self._ctx_buckets = sorted(set(tg) | {ecfg.blocks_per_seq})
        if self._ragged:
            # the ragged kernel owns the FULL window with per-row cost:
            # the context-bucket ladder collapses to one entry, and no
            # dispatch ever keys on the longest running sequence again
            self._ctx_buckets = [ecfg.blocks_per_seq]
        self._decode_fns: Dict[Tuple[int, int], Any] = {}
        # speculative decoding: a host-side prompt-lookup drafter plus one
        # multi-token verify executable per (ctx_bucket, batch_bucket) —
        # same dispatch grid as decode, k+1 positions per call
        self._verify_fns: Dict[Tuple[int, int], Any] = {}
        self._drafter = None
        self.spec = None
        if ecfg.speculative_enabled:
            from .speculative import PromptLookupDrafter, SpecStats

            self._drafter = PromptLookupDrafter(
                ecfg.num_speculative_tokens,
                ecfg.ngram_prompt_lookup_max, ecfg.ngram_prompt_lookup_min)
            self.spec = SpecStats()
            # rejection-sampling uniforms (temperature > 0 acceptance):
            # host-side, own stream — device rng folds stay byte-identical
            # to vanilla decode
            self._spec_rng = np.random.default_rng(ecfg.seed + 0x5EC)
        # fused mixed-phase step (SHAI_FUSED_STEP, default off): decode and
        # the chunked-prefill continuation share ONE ragged executable per
        # batch bucket — the decode (ctx x batch), ragged-continuation, and
        # cached-admission-continuation ladders all collapse into it. Rides
        # the ragged kernel (rows fuse by pure layout, the kernel never
        # learns phases) and stays out of speculative engines (verify owns
        # multi-token dispatch there). Off keeps the laddered engine as the
        # token-exact oracle the fused differential tests compare against.
        self._fused = bool(_env_flag("SHAI_FUSED_STEP", False)
                           and self._ragged
                           and not ecfg.speculative_enabled)
        self._fused_fns: Dict[int, Any] = {}
        # deferred continuation window: an intermediate chunk parks its
        # (ids, n_text, table, start) here and rides the NEXT decode
        # dispatch as the fused executable's chunk section instead of
        # paying its own dispatch; consumed by _take_chunk_args, flushed
        # by every path that would skip or reorder around that dispatch
        self._pending_chunk: Optional[tuple] = None
        self._null_chunk: Optional[list] = None
        # copy-on-write KV fan-out (SHAI_KV_COW, default off): an n>1
        # sampling group admits ONE shared prefill and every sibling forks
        # the prompt blocks copy-on-write (cache.fork_sequence); the first
        # divergent decode write pays the one block copy
        self._kv_cow = bool(_env_flag("SHAI_KV_COW", False))
        # fan-out bookkeeping: parent request id -> live sibling rids (the
        # serving layer cancels/deadlines the group as one unit)
        self._fanout_groups: Dict[int, set] = {}
        self._rid_parent: Dict[int, int] = {}
        self._sample1 = jax.jit(sample_logits)
        from .runner import token_logprobs

        self._lp1 = jax.jit(token_logprobs)  # prefill-logit logprob readout
        self._cross_kv = None      # mllama slot-indexed encoder cache
        self._cross_embed = None   # jitted states -> per-layer k/v
        self._has_image = np.zeros((ecfg.max_num_seqs,), np.float32)
        self._cross_len = np.full((ecfg.max_num_seqs,), max(cross_seq_len, 1),
                                  np.int32)
        if model_cfg.cross_attention_layers:
            from .runner import make_cross_kv, make_cross_slot_write

            dt = jnp.bfloat16 if ecfg.dtype == "bfloat16" else jnp.float32
            shape = (ecfg.max_num_seqs, cross_seq_len,
                     model_cfg.n_kv_heads, model_cfg.head_dim)
            csh = (None if self.shardings is None
                   else self.shardings.cross_pool(
                       len(model_cfg.cross_attention_layers)))

            def zeros(i, name):
                z = jnp.zeros(shape, dt)
                if csh is not None:
                    z = jax.device_put(z, csh[i][name])
                return z

            self._cross_kv = [
                {"k": zeros(i, "k"), "v": zeros(i, "v")}
                for i in range(len(model_cfg.cross_attention_layers))]
            self._cross_embed = make_cross_kv(model_cfg)
            self._cross_write = make_cross_slot_write(model_cfg)
        self.waiting: deque[Request] = deque()
        self.slots: List[Optional[_Running]] = [None] * ecfg.max_num_seqs
        # multi-tenant QoS (SHAI_QOS, default off): the weighted-fair
        # scheduler kernel every admission dequeue routes through. OFF
        # means _schedule_head never touches the deque — the FIFO engine
        # stays token-exact vs the pre-QoS baseline (the differential
        # contract tests/test_qos.py holds across both async disciplines).
        self._sched = (_qos.WeightedFairScheduler.from_env()
                       if _qos.qos_enabled() else None)
        # per-tenant step gauges are computed only once a tenant-tagged
        # request (or QoS itself) shows up — zero added per-step work on
        # an untagged FIFO engine
        self._tenant_seen = self._sched is not None
        self._warmed = False
        # serving-grade latency instruments (vLLM's TTFT/TPOT), exported by
        # the serving layer's /stats — TTFT includes queue time; TPOT is
        # per-token decode pace after the first token
        from ..utils.latency import LatencyCollector

        self.ttft = LatencyCollector()
        self.tpot = LatencyCollector()
        # step telemetry (obs): per-step occupancy/KV/preemption records +
        # TTFT/TPOT/queue-wait histograms, exported by the serving layer as
        # Prometheus histograms and flight-recorder step records
        self.obs = StepTelemetry(total_blocks=ecfg.total_blocks)
        # conformance layer (obs): SLO burn rates, perf-model sentinel, and
        # the live HBM ledger ride the telemetry object so ONE provider
        # seam (ModelService.engine_telemetry) feeds /stats, /metrics, the
        # flight recorder, and the failover controller alike
        self.obs.slo = SloEngine.maybe_from_env(SloTargets(
            ttft_ms=ecfg.slo_ttft_ms, tpot_ms=ecfg.slo_tpot_ms,
            error_rate=ecfg.slo_error_rate))
        self.obs.sentinel = obs_sentinel.PerfSentinel.from_env(
            default_key=(ecfg.perf_projection
                         or obs_sentinel.default_projection_key(
                             ecfg.model, quantized=ecfg.quantization == "int8",
                             tp=ecfg.tensor_parallel_size)))
        hbm_limit = 0.0
        try:
            from ..core.budget import GIB, detect_hbm_gib

            if jax.local_devices()[0].platform != "cpu":
                hbm_limit = detect_hbm_gib(jax.local_devices()[0]) * GIB
        except Exception:  # deviceless dryruns must still boot
            pass
        self.obs.hbm = HbmLedger(bytes_limit=hbm_limit)
        # host KV tier counters ride the same ONE provider seam as the
        # conformance instruments: /stats, /metrics, and the admission
        # gate all read them off the telemetry object
        self.obs.kvtier = self.cache.tier
        # kvnet transport counters (disaggregated serving): constructed
        # HERE so they ride the same seam from boot; the serving layer's
        # KvNetClient and the /kv/blocks route share this one object
        if self.cache.tier is not None:
            from ..kvnet.client import KvNetStats

            self.obs.kvnet = KvNetStats()
        # fleet KV fabric (kvnet.directory): the peer-probe third rung of
        # the admission ladder. Constructed HERE, env-gated, so a two-pod
        # fabric arms with nothing but SHAI_KVFABRIC[_PEERS]; fabric-off
        # leaves _kvfabric None and the ladder byte-identical to the
        # pre-fabric engine (the strict-no-op differential contract)
        self._kvfabric = None
        if self.cache.tier is not None:
            from ..kvnet import directory as _kvdir

            if _kvdir.fabric_enabled():
                self._kvfabric = _kvdir.FabricProbe(
                    self.cache.tier, kvnet_stats=self.obs.kvnet)
                self.obs.kvfabric = self._kvfabric.stats
        # live-migration counters (kvnet.migrate): built unconditionally —
        # even a tier-less pod participates in the ladder's cold rung
        # (manifest-only migration), and the shai_migrate_* families must
        # export wherever a drain can ship or a peer can resume
        from ..kvnet.migrate import MigrateStats

        self.obs.migrate = MigrateStats()
        # the QoS scheduler rides the same seam: /stats -> "qos" reads its
        # pick/aging counters next to the ledger's per-tenant usage
        self.obs.qos_sched = self._sched
        from ..obs.util import env_int as _env_int

        # ledger cadence: every Nth step (default every step — cheap on
        # the tiny tiers; production tiers with thousands of blocks can
        # widen it, the drift windows are sample-count-based either way)
        self._hbm_every = max(1, _env_int("SHAI_HBM_SAMPLE_EVERY", 1))
        self._hbm_dev = jax.local_devices()[0]
        self._weights_bytes: Optional[int] = None
        self._kv_pool_bytes = 0
        self._cross_bytes = 0
        self._tokens_this_step = 0
        self._n_exec_last = 0
        self._last_rollback_tokens = 0
        self._step_kind = "idle"
        # async pipelined decode (SHAI_ASYNC_DECODE, default on): device-
        # resident batch arrays + at most ONE in-flight lookahead dispatch.
        # The lock-step path stays intact as the differential oracle.
        self._async = _resolve_async()
        self._pipe: Optional[InflightStep] = None
        self._res = ResidentBatch()
        self._t_fetch = 0.0          # last decode-readback completion
        self._last_decode_step = -2  # step-gap continuity gate
        self._ids = itertools.count()
        self._step_count = 0
        self._rng = jax.random.PRNGKey(ecfg.seed)
        self.finished: List[Finished] = []
        self._done_this_step: List[Finished] = []

    # -- public API --------------------------------------------------------

    def add_request(self, prompt_ids: Sequence[int],
                    params: Optional[SamplingParams] = None,
                    prefix: Optional[np.ndarray] = None,
                    cross_states: Optional[np.ndarray] = None,
                    cross_len: int = 0, on_token=None,
                    deadline_at: float = 0.0,
                    priority: int = _qos.PRIORITY_NORMAL,
                    tenant: str = "",
                    already_generated: Optional[Sequence[int]] = None,
                    already_lp: Optional[list] = None,
                    orig_n_prompt: int = -1,
                    parent_rid: int = -1,
                    kv_holders: Optional[Sequence[str]] = None,
                    traceparent: str = "",
                    idem_key: str = "") -> int:
        params = (params or SamplingParams()).clamp(self.ecfg)
        if not prompt_ids:
            raise ValueError("empty prompt")
        if cross_states is not None:
            if self._cross_kv is None:
                raise ValueError("model has no cross-attention layers")
            if cross_states.shape != (self.cross_seq_len, self.cfg.dim):
                raise ValueError(
                    f"cross_states must be [{self.cross_seq_len}, "
                    f"{self.cfg.dim}], got {cross_states.shape}")
            if not 0 <= cross_len <= self.cross_seq_len:
                raise ValueError(
                    f"cross_len={cross_len} out of [0, {self.cross_seq_len}]")
        if prefix is not None and self._cross_kv is not None:
            # a prefix on a cross model would assert deep inside make_prefill
            # and kill the engine loop — reject it as a per-request error
            raise ValueError(
                "mllama models condition on cross_states, not a soft prefix")
        n_prefix = 0 if prefix is None else int(prefix.shape[0])
        if n_prefix >= self.buckets.max:
            raise ValueError(
                f"prefix of {n_prefix} tokens exceeds the largest prefill "
                f"bucket {self.buckets.max}")
        if n_prefix:
            # soft-prefix requests are bucket-bound: the prefix occupies
            # positions inside the single prefill call
            max_prompt = self.buckets.max - n_prefix
        else:
            # text AND cross-attention prompts chunk past the largest bucket
            # (the continuation ladder carries cross args on mllama engines)
            # up to the model-length budget: full chunks only, room left to
            # generate
            max_prompt = self._chunk_cap
        if len(prompt_ids) > max_prompt:
            prompt_ids = list(prompt_ids)[-max_prompt:]  # keep the tail
        rid = next(self._ids)
        # n>1 sampling fan-out (SHAI_KV_COW): siblings share one parent id
        # so the group cancels/expires as a unit and _admit_fanout can
        # recognize a fully-queued group. -2 marks the group leader — its
        # OWN rid becomes the parent (the submitter can't know rids yet).
        if parent_rid == -2:
            parent_rid = rid
        if parent_rid >= 0:
            self._rid_parent[rid] = parent_rid
            self._fanout_groups.setdefault(parent_rid, set()).add(rid)
        priority = min(max(int(priority), _qos.PRIORITY_HIGH),
                       _qos.PRIORITY_LOW)
        tenant = _qos.sanitize_tenant(tenant)
        if tenant or priority != _qos.PRIORITY_NORMAL:
            self._tenant_seen = True
        if self._tenant_seen:
            # gated: an untagged FIFO pod never pays the telemetry lock
            # here and never grows a tenant label set — the shai_tenant_*
            # families appear only once a tenant tag (or QoS) is live
            self.obs.count_tenant_request(tenant, _qos.class_name(priority))
        # resume support (live migration, kvnet.migrate): a request that
        # migrated in from a peer carries its pre-migration output — the
        # same prompt-suffix semantics a preemption resume uses, so the
        # admission ladder needs nothing new
        self.waiting.append(Request(rid, list(prompt_ids), params,
                                    prefix=prefix, cross_states=cross_states,
                                    cross_len=cross_len, on_token=on_token,
                                    deadline_at=deadline_at,
                                    t_submit=time.monotonic(),
                                    priority=priority, tenant=tenant,
                                    already_generated=list(
                                        already_generated or []),
                                    already_lp=list(already_lp or []),
                                    orig_n_prompt=orig_n_prompt,
                                    parent_rid=parent_rid,
                                    kv_holders=[str(u) for u in
                                                (kv_holders or [])],
                                    traceparent=str(traceparent or ""),
                                    idem_key=str(idem_key or "")))
        return rid

    def fanout_siblings(self, rid: int) -> List[int]:
        """Live request ids of the fan-out group containing ``rid`` (always
        includes ``rid`` itself). The engine loop cancels through this so a
        client disconnect on an n>1 request aborts the WHOLE group — the n
        choices serve one HTTP response; decoding orphaned siblings would
        burn pool blocks for nobody."""
        parent = self._rid_parent.get(rid)
        if parent is None:
            return [rid]
        return sorted(self._fanout_groups.get(parent, {rid}) | {rid})

    def cancel(self, req_id: int) -> Optional[Finished]:
        """Abort a request wherever it is (queue, mid-prefill, or decoding),
        reclaiming its slot and blocks. Returns the partial Finished (reason
        ``"cancelled"``), or None if the id is unknown/already finished.
        Used by streamed requests that hit a client-side stop sequence or
        whose client disconnected — the engine would otherwise decode to
        max_new_tokens for nobody."""
        return self._abort(req_id, "cancelled")

    # -- live migration (kvnet.migrate) ------------------------------------

    def _release_slot(self, s: "_Running") -> None:
        """THE slot teardown triple — release the sequence's blocks and
        clear the slot — shared by every path that retires a running
        slot (finish, abort, preempt, speculative finish, migrate), so
        the teardown contract cannot drift between them."""
        self.cache.release(s.req.req_id)
        self.slots[s.slot] = None
        self._has_image[s.slot] = 0.0

    def _manifest_of(self, req: Request, resume_prompt, emitted,
                     remaining: int, lps, hashes) -> Dict[str, Any]:
        """The resumable-state manifest a peer pod re-admits from: plain
        ints/floats/strings only (it crosses pods as JSON). ``rng_step``
        is the origin engine's fold step at capture — informational: the
        greedy oracle is fold-free, and a sampled resume re-derives its
        stream from the peer's own seed by design."""
        p = req.params
        now = time.monotonic()
        man: Dict[str, Any] = {
            "v": 1,
            "prompt_ids": [int(t) for t in resume_prompt],
            "generated": [int(t) for t in emitted],
            "n_prompt": int(req.orig_n_prompt),
            "params": {
                "temperature": float(p.temperature),
                "top_k": int(p.top_k), "top_p": float(p.top_p),
                "max_new_tokens": int(remaining),
                "eos_id": int(p.eos_id), "logprobs": int(p.logprobs),
            },
            "priority": int(req.priority), "tenant": req.tenant,
            "deadline_ms": (max(0.0, (req.deadline_at - now) * 1000.0)
                            if req.deadline_at else 0.0),
            "rng_step": int(self._step_count),
            "hashes": [int(h) for h in hashes],
        }
        if req.idem_key:
            # the key survives migration: the peer's resume admits under
            # the SAME key, so a duplicated resume replay dedupes there
            man["idem_key"] = req.idem_key
        if p.logprobs and lps is not None:
            man["lps"] = list(lps)
        return man

    def snapshot_sequence(self, req_id: int) -> Optional[Dict[str, Any]]:
        """Capture a request's resumable state (the live-migration seam):
        prompt + generated token ids, remaining sampling budget, QoS
        identity, deadline remainder, and the chain hashes of the
        full-block KV run this call BANKS in the host tier — generated
        blocks included, via :meth:`~.cache.PagedKVCache.demote_token_run`
        (the ``demote_prompt_run`` positional gather, extended past the
        prompt). Loop-thread only: the snapshot happens under the
        engine's single-owner discipline; the SHIP happens on a serving
        thread outside it. Read-only with respect to the request's
        lifecycle — :meth:`migrate_out` is snapshot + finish."""
        for r in self.waiting:
            if r.req_id == req_id:
                # queued: no KV exists yet — a pure prompt replay (the
                # cold rung; the peer recomputes from scratch)
                return self._manifest_of(
                    r, r.prompt_ids, r.already_generated,
                    r.params.max_new_tokens,
                    r.already_lp if r.params.logprobs else None, [])
        for s in self.slots:
            if s is None or s.req.req_id != req_id:
                continue
            req, p = s.req, s.req.params
            if s.prefill_cursor is not None:
                # mid-chunk: nothing generated this segment; bank the
                # chunks already encoded (registered per chunk) so the
                # peer's warm admission skips them
                _, hashes = self.cache.demote_token_run(
                    req_id, req.prompt_ids[:s.prefill_cursor])
                return self._manifest_of(
                    req, req.prompt_ids, req.already_generated,
                    p.max_new_tokens,
                    req.already_lp if p.logprobs else None, hashes)
            committed = s.generated + [s.pending_token]
            # KV exists for prompt+generated only — the pending token's
            # write lands with the NEXT dispatch, which never runs here
            _, hashes = self.cache.demote_token_run(
                req_id, req.prompt_ids + s.generated)
            lps = None
            if p.logprobs:
                lps = req.already_lp + s.lps[:len(committed)]
            return self._manifest_of(
                req, req.prompt_ids + committed,
                req.already_generated + committed,
                p.max_new_tokens - len(committed), lps, hashes)
        return None

    def migrate_out(self, req_id: int) -> Optional[Finished]:
        """Finish a request with stop reason ``"migrated"``, its
        :meth:`snapshot_sequence` manifest attached: the serving layer
        ships the manifest + the banked KV run to a healthy peer and the
        request CONTINUES there. A pending token that already completes
        the request finishes normally instead (``eos``/``length`` — there
        is nothing left to migrate). Loop-thread only. Returns None for
        an unknown/finished id."""
        if any(((r.prefix is not None or r.cross_states is not None)
                and r.req_id == req_id)
               for r in self.waiting) or any(
                   s is not None and s.req.req_id == req_id
                   and (s.req.prefix is not None
                        or s.req.cross_states is not None)
                   for s in self.slots):
            # multimodal state (soft prefix / cross states) does not
            # serialize into the manifest — not migratable; the drain
            # path falls back to the legacy wait-then-stop for these
            return None
        for i, r in enumerate(self.waiting):
            if r.req_id == req_id:
                man = self.snapshot_sequence(req_id)
                del self.waiting[i]
                r.obs_extra["t_migrate_cut"] = time.monotonic()
                return Finished(
                    req_id, list(r.already_generated), r.orig_n_prompt,
                    "migrated",
                    logprobs=(list(r.already_lp)
                              if r.params.logprobs else None),
                    timing=self._timing_of(r), migration=man)
        cut_slot = next((s for s in self.slots
                         if s is not None and s.req.req_id == req_id), None)
        if cut_slot is None:
            return None
        # the in-flight lookahead may hold an extra sampled token for
        # this slot: retire it first so the snapshot sees current host
        # mirrors (the extra token is the discarded lookahead, exactly
        # the _abort contract)
        self._flush_pipeline("migrate", req=cut_slot.req)
        for s in self.slots:
            if s is None or s.req.req_id != req_id:
                continue
            s.req.obs_extra["t_migrate_cut"] = time.monotonic()
            req, p = s.req, s.req.params
            if s.prefill_cursor is None:
                committed = s.generated + [s.pending_token]
                if (s.pending_token == p.eos_id
                        or len(committed) >= p.max_new_tokens):
                    # the sampled pending token already ends the request
                    # — finish it here (the _preempt_lowest close-out
                    # semantics), nothing resumable remains
                    if (req.on_token is not None
                            and s.pending_token != p.eos_id):
                        req.on_token(s.pending_token)
                    emitted = req.already_generated + committed
                    lps = (req.already_lp + s.lps) if p.logprobs else None
                    if emitted and emitted[-1] == p.eos_id:
                        emitted = emitted[:-1]
                        if lps:
                            lps = lps[:-1]
                        reason = "eos"
                    else:
                        reason = "length"
                    self._record_tpot(s)
                    self._release_slot(s)
                    return Finished(req_id, emitted, req.orig_n_prompt,
                                    reason, logprobs=lps,
                                    timing=self._timing_of(req, s.t_first))
                if req.on_token is not None:
                    # the pending token WILL be in the final output (the
                    # peer resumes past it) — stream it now, exactly-once
                    # -per-output-token (the preemption contract)
                    req.on_token(s.pending_token)
            man = self.snapshot_sequence(req_id)
            self._record_tpot(s)
            emitted = req.already_generated + (
                [] if s.prefill_cursor is not None
                else s.generated + [s.pending_token])
            lps = None
            if p.logprobs:
                lps = req.already_lp + (
                    [] if s.prefill_cursor is not None
                    else s.lps[:len(s.generated) + 1])
            self._release_slot(s)
            return Finished(req_id, emitted, req.orig_n_prompt,
                            "migrated", logprobs=lps,
                            timing=self._timing_of(req, s.t_first),
                            migration=man)
        return None

    def _abort(self, req_id: int, reason: str) -> Optional[Finished]:
        """THE teardown for a request leaving early (``cancelled`` /
        ``timeout``): remove it from the queue or its slot, release exactly
        its cache blocks, and return the partial Finished."""
        for i, r in enumerate(self.waiting):
            if r.req_id == req_id:
                del self.waiting[i]
                return Finished(req_id, list(r.already_generated),
                                r.orig_n_prompt, reason,
                                logprobs=(list(r.already_lp)
                                          if r.params.logprobs else None),
                                timing=self._timing_of(r))
        abort_slot = next((s for s in self.slots
                           if s is not None and s.req.req_id == req_id),
                          None)
        if abort_slot is not None:
            # the in-flight lookahead step (async decode) may have computed
            # one extra token for this slot: retire it so the host mirrors
            # are current before teardown — the extra token is discarded
            # (never emitted) and its block reservation frees with the
            # slot's release below, same flush
            self._flush_pipeline(reason, req=abort_slot.req)
        for s in self.slots:
            if s is not None and s.req.req_id == req_id:
                self._record_tpot(s)
                self._release_slot(s)
                return Finished(
                    req_id, s.req.already_generated + s.generated,
                    s.req.orig_n_prompt, reason,
                    logprobs=((s.req.already_lp + s.lps[:len(s.generated)])
                              if s.req.params.logprobs else None),
                    timing=self._timing_of(s.req, s.t_first))
        return None

    def _expire_deadlines(self) -> None:
        """Finish every request whose deadline passed — queued, mid-chunk,
        or decoding — with stop reason ``"timeout"``. Step-granular: a
        request is at most one engine step late, and its blocks/slot free
        the same step instead of decoding to max_new_tokens for a caller
        that already gave up.

        ONE linear pass over the queue: the old shape collected expired
        ids and re-scanned ``waiting`` once per id through ``_abort`` —
        O(n^2) exactly when an adversarial tenant floods the queue with
        short deadlines. The rebuild preserves arrival order within and
        across priority classes, and it runs BEFORE the weighted-fair
        head selection, so an expired request's queue slot is visible to
        the scheduler (and to admission) the very same step."""
        now = time.monotonic()
        expired: List[Request] = [r for r in self.waiting
                                  if 0.0 < r.deadline_at <= now]
        if expired:
            kept = [r for r in self.waiting if not (0.0 < r.deadline_at
                                                    <= now)]
            self.waiting.clear()
            self.waiting.extend(kept)
            for r in expired:
                log.warning("req %d exceeded its deadline "
                            "(%d tokens generated)", r.req_id,
                            len(r.already_generated))
                self._finish(Finished(
                    r.req_id, list(r.already_generated), r.orig_n_prompt,
                    "timeout",
                    logprobs=(list(r.already_lp)
                              if r.params.logprobs else None),
                    timing=self._timing_of(r)))
        for rid in [s.req.req_id for s in self.slots
                    if s is not None and 0.0 < s.req.deadline_at <= now]:
            fin = self._abort(rid, "timeout")
            if fin is not None:
                log.warning("req %d exceeded its deadline "
                            "(%d tokens generated)", rid, len(fin.token_ids))
                self._finish(fin)

    @property
    def max_prompt_len(self) -> int:
        """Longest prompt the engine accepts un-truncated: the
        chunked-prefill cap, which ``add_request`` enforces exactly for
        text AND cross-attention prompts (≥ the largest bucket whenever
        ``max_model_len`` exceeds it; soft-prefix requests are additionally
        capped in the serving layer). The serving layer truncates its
        tokenizer output to THIS, not to the largest bucket."""
        return self._chunk_cap

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)

    @property
    def n_waiting(self) -> int:
        return len(self.waiting)

    @property
    def n_running(self) -> int:
        return sum(s is not None for s in self.slots)

    @property
    def n_chunking(self) -> int:
        return sum(s is not None and s.prefill_cursor is not None
                   for s in self.slots)

    def step(self) -> List[Finished]:
        """Admit (at most one prefill), then decode the running batch.

        Returns every request that finished during this step, whatever the
        path (decode EOS/length, admission rejection, preemption close-out).

        Two dispatch disciplines behind one contract (``SHAI_ASYNC_DECODE``):
        the async path pipelines decode dispatches one step ahead of the
        host readback; the lock-step path is the reference oracle. Both
        commit/stream/finish the same tokens on the same ``step()`` call.
        """
        if self._async:
            return self._step_async()
        return self._step_sync()

    def _step_sync(self) -> List[Finished]:
        """Lock-step step: marshal -> dispatch -> readback -> bookkeeping,
        one blocking device round-trip per decode step."""
        t0 = time.monotonic()
        self._step_count += 1
        self._done_this_step = []
        self._tokens_this_step = 0
        self._step_kind = "idle"
        inj = _faults.get()
        if inj.active:
            # chaos sites: step latency/stall (watchdog + deadline fodder)
            # and step crash (the engine-loop-death path)
            inj.sleep_at(_faults.ENGINE_STEP)
            inj.raise_at(_faults.ENGINE_STEP)
        # expire BEFORE admission: a queued request already past its
        # deadline must not be admitted into a prefill nobody waits for
        self._expire_deadlines()
        self._admit_phase()
        if any(s is not None for s in self.slots):
            self._decode_step()
        self._flush_chunk()  # a deferred window never outlives its step
        self._record_step(time.monotonic() - t0)
        return self._done_this_step

    def _schedule_head(self) -> None:
        """Weighted-fair head selection (SHAI_QOS): rotate the scheduler-
        picked class's oldest request to ``waiting[0]`` so every admission
        path below dequeues class-aware without changing its mechanics.
        Pure host arithmetic (hot-path safe); a strict no-op with QoS off
        or a single-class queue — the token-exactness seam."""
        if self._sched is not None:
            _qos.schedule_rotate(self.waiting, self._sched)

    def _admit_phase(self) -> None:
        """One step's chunk-continuation + admission ladder (shared by the
        lock-step and async step bodies)."""
        chunking = [s for s in self.slots
                    if s is not None and s.prefill_cursor is not None]
        if chunking:
            # one continuation chunk per step: the long prompt encodes
            # incrementally while the running batch keeps decoding below
            self._continue_prefill(chunking[0])
        # class-aware dequeue BEFORE the ladder branches on the head: the
        # branch taken (prefix/cached/long/cross/batch) must be the branch
        # for the request fairness actually selected
        self._schedule_head()
        # admission proceeds even while a long prompt chunks (its slot is
        # untouched) — queued short prompts must not pay k chunk-steps of
        # TTFT; only a SECOND long prompt waits for the active chunker
        if self.waiting and self.waiting[0].prefix is not None:
            self._admit_one()       # soft-prefix: bucket-bound single-seq
        elif (self._kv_cow and self.waiting
              and self.waiting[0].parent_rid >= 0
              and self._admit_fanout()):
            pass                    # CoW fan-out: one prefill, K forks
        elif (self.cache.prefix_caching and self.waiting
              and self._admit_cached()):
            pass                    # cached-prefix admission handled it
        elif (self.waiting
              and len(self.waiting[0].prompt_ids) > self.buckets.max):
            if not chunking:
                self._admit_long()  # chunked prefill (text or cross)
        elif self.waiting and self.waiting[0].cross_states is not None:
            self._admit_one()       # short multimodal: single-seq
        else:
            self._admit_batch()

    # -- async pipelined decode (SHAI_ASYNC_DECODE, the default) -----------
    #
    # The decode hot loop never makes the device wait on the host: step N+1
    # is dispatched (JAX dispatch is async) with step N's device-side
    # sampled tokens fed straight back as its inputs, BEFORE step N's
    # results are read back; all of step N's host bookkeeping (EOS/length/
    # stop checks, on_token streaming, logprobs assembly, obs records) then
    # runs while step N+1 executes. Any event that changes batch
    # composition or control flow — join/finish/preempt, deadline expiry,
    # cancellation, spec-decode entry, bucket change — flushes the pipeline
    # first: the in-flight step is retired, surviving slots' host mirrors
    # catch up, and a finished/cancelled slot's extra computed token is
    # discarded (never emitted; its reservation frees with the slot).
    #
    # Token-exactness vs the lock-step oracle holds by construction: the
    # dispatch composition, batch-row packing, and rng folds of step k are
    # all functions of state known BEFORE step k-1's readback (a finishing
    # slot participates in exactly one extra dispatch in both disciplines),
    # so pipelining only reorders host work, never device inputs.

    def _step_async(self) -> List[Finished]:
        t0 = time.monotonic()
        self._step_count += 1
        self._done_this_step = []
        self._tokens_this_step = 0
        self._step_kind = "idle"
        inj = _faults.get()
        if inj.active:
            inj.sleep_at(_faults.ENGINE_STEP)
            inj.raise_at(_faults.ENGINE_STEP)
        now = time.monotonic()
        deadline_due = (
            any(0.0 < r.deadline_at <= now for r in self.waiting)
            or any(s is not None and 0.0 < s.req.deadline_at <= now
                   for s in self.slots))
        chunking = any(s is not None and s.prefill_cursor is not None
                       for s in self.slots)
        # the steady (pure-decode) path needs no host-side inputs at all;
        # anything else — admission work, chunked prefill, a due deadline,
        # a drafter wanting the pending token — is an event step
        if (self._pipe is not None and not self.waiting and not chunking
                and not deadline_due and self._drafter is None):
            self._steady_step()
        else:
            if self._pipe is not None:
                self._flush_pipeline(
                    "deadline" if deadline_due else
                    "admission" if self.waiting else
                    "chunking" if chunking else "spec")
            self._expire_deadlines()
            self._admit_phase()
            if any(s is not None for s in self.slots):
                self._decode_dispatch()
            self._flush_chunk()  # deferred window never outlives its step
        self._record_step(time.monotonic() - t0)
        return self._done_this_step

    def _steady_step(self) -> None:
        """Pipelined decode step: dispatch N+1 on device feedback, then
        retire step N and do its host bookkeeping while N+1 runs."""
        prev = self._pipe
        running = self._running_slots()
        if not running:
            # the previous commit finished every slot; retire the trailing
            # dispatch (its tokens are the discarded extra) and go idle
            self._flush_pipeline("drained")
            return
        if composition_sig(running,
                           self._batch_bucket(len(running))) != prev.sig:
            # join/finish changed the compacted batch view: the device
            # feedback arrays are packed for the OLD rows — re-marshal
            self._flush_pipeline("recompose")
            self._decode_dispatch()
            return
        # price the whole step's growth before touching the allocator: the
        # steady path must never recompute-preempt around an in-flight
        # lookahead; pool pressure falls back to the grow-with-preemption
        # ladder below
        need = sum(self.cache.blocks_to_extend(s.req.req_id, 1)
                   for s in running)
        if need > self.cache.n_available:
            self._flush_pipeline("kv_pressure")
            self._decode_dispatch()
            return
        self._step_kind = "decode"
        for s in running:
            self.cache.extend(s.req.req_id, 1)
        Bb = self._batch_bucket(len(running))
        _, decode = self._decode_for(self._max_ctx_blocks(running),
                                     len(running))
        self._note_dispatch_pad(running, Bb)
        a = self._res.refresh(self, running, Bb)  # tables re-up if grown
        rng = jax.random.fold_in(self._rng, self._step_count * 2)
        tokens_dev, pos_dev = prev.nxt, prev.pos_next
        prev.pos_next = None  # donated into this dispatch
        self._dispatch_async(decode, running, Bb, tokens_dev, pos_dev,
                             a, rng)
        t_f = self._retire_pipe(prev)
        # the dispatch beat the readback: the recorded inter-step gap is
        # (clamped) zero — the device went straight into step N+1
        self.obs.step_gap.observe(max(0.0, self._pipe.t_dispatch - t_f))
        self._commit_pending(running)

    def _decode_dispatch(self) -> None:
        """Event-path decode: host-marshaled dispatch (mirrors are current)
        with the readback DEFERRED to the next step — re-establishes the
        pipeline in the same call that handled the event."""
        if self._drafter is not None and self._spec_step():
            self._step_kind = "spec"
            return
        self._step_kind = "decode"
        self._grow_running(lambda s: 1)
        running = self._running_slots()
        if not running:
            # chunk-only step (every live slot is mid-prefill): nothing
            # rides the decode dispatch, so the window pays its own
            self._flush_chunk()
            return
        Bb = self._batch_bucket(len(running))
        n_exec = self.n_executables
        _, decode = self._decode_for(self._max_ctx_blocks(running),
                                     len(running))
        self._note_dispatch_pad(running, Bb)
        a = self._res.refresh(self, running, Bb)
        tokens = np.zeros((Bb,), np.int32)
        pos = np.zeros((Bb,), np.int32)
        for i, s in enumerate(running):
            tokens[i] = s.pending_token
            pos[i] = self.cache.seq(s.req.req_id).n_tokens - 1
        rng = jax.random.fold_in(self._rng, self._step_count * 2)
        self._dispatch_async(decode, running, Bb, jnp.asarray(tokens),
                             jnp.asarray(pos), a, rng,
                             gap_ok=self.n_executables == n_exec)
        self._commit_pending(running)

    def _dispatch_async(self, decode, running, Bb: int, tokens_dev,
                        pos_dev, a, rng, gap_ok: bool = True) -> None:
        """Enqueue one feedback-decode dispatch and record it in-flight.

        ``gap_ok=False`` suppresses the step-gap observation (the caller
        compiled a new executable this step — warmup, not a dispatch gap).
        """
        args = [self.params, self.cache.kv, tokens_dev, pos_dev,
                a["tables"], a["active"], rng, a["temp"], a["topk"],
                a["topp"]]
        if self._cross_kv is not None:
            args += [self._cross_kv, a["has_image"], a["slot_idx"],
                     a["cross_len"]]
        cold = self._pipe is None
        t_d = time.monotonic()
        with annotate("engine.decode"):
            (self.cache.kv, nxt, pos_next, top_ids, top_lp,
             tok_lp) = decode(*args)
        if cold and gap_ok and self._t_fetch \
                and self._last_decode_step == self._step_count - 1:
            # flush/cold step: the dispatch had to wait for the readback —
            # this gap is the serialization cost of the event
            self.obs.step_gap.observe(max(0.0, t_d - self._t_fetch))
        self._last_decode_step = self._step_count
        self._pipe = InflightStep(
            sig=composition_sig(running, Bb), running=list(running),
            nxt=nxt, pos_next=pos_next, top_ids=top_ids, top_lp=top_lp,
            tok_lp=tok_lp,
            want_lp=any(s.req.params.logprobs for s in running),
            t_dispatch=t_d)

    def _retire_pipe(self, pipe: InflightStep) -> float:
        """Host half of a dispatched step: fetch the sampled tokens (the
        only blocking device sync in the async loop) and mirror them into
        ``pending_token`` + logprob entries. Slots that finished or were
        cancelled since the dispatch are skipped — their extra token is
        exactly the discarded lookahead. Returns the fetch stamp."""
        if pipe.want_lp:
            # shai-lint: allow(host-sync) THE one blocking fetch of the
            # pipeline: retiring step N must read its sampled tokens (and
            # logprobs) back — everything else overlaps step N+1
            nxt, top_ids, top_lp, tok_lp = jax.device_get(
                (pipe.nxt, pipe.top_ids, pipe.top_lp, pipe.tok_lp))
        else:
            # shai-lint: allow(host-sync) same fetch, logprob-free shape
            nxt = np.asarray(pipe.nxt)
            top_ids = top_lp = tok_lp = None
        t_f = time.monotonic()
        self._t_fetch = t_f
        self._apply_sampled(pipe.running, nxt, top_ids, top_lp, tok_lp)
        return t_f

    def _flush_pipeline(self, reason: str,
                        req: Optional[Request] = None) -> None:
        """Retire the in-flight lookahead (no-op when none): the explicit
        pipeline flush every composition/control-flow event pays. Counted
        per reason — a high flush rate is the 'pipeline never gets to
        stream' signal on ``/metrics``. ``req``: the request this flush is
        attributable to (abort/migrate/kv-restore sites know one) — its
        trace's decode span carries the per-request count."""
        pipe, self._pipe = self._pipe, None
        if pipe is None:
            return
        self._retire_pipe(pipe)
        self.obs.count_flush(reason)
        if req is not None:
            req.obs_extra["pipeline_flushes"] = \
                req.obs_extra.get("pipeline_flushes", 0.0) + 1.0

    def finish_pending(self) -> None:
        """Retire any in-flight lookahead step — the engine loop calls this
        when the engine goes idle so host mirrors don't sit one step stale
        across an idle gap (and the last step's buffers free)."""
        self._flush_pipeline("idle")
        # idle breaks step-gap continuity: the step COUNTER does not tick
        # while the loop waits for work, so without this reset the first
        # dispatch of the next burst would book the whole wall-clock idle
        # gap as a dispatch gap (seen live: a 1.5 s "gap" between bursts)
        self._last_decode_step = -2

    def _record_step(self, duration_s: float) -> None:
        """One obs step record per engine step — occupancy, KV pressure,
        rollback delta, speculative counters at step end — plus the
        conformance feeds: the perf sentinel's (tokens, busy-seconds)
        sample and one HBM ledger tick."""
        rb = self.cache.rollback_tokens
        tenants = None
        if self._tenant_seen:
            # per-tenant occupancy gauges (waiting, running): bounded by
            # the queue+slot walk this step already paid; skipped entirely
            # on engines that never saw a tenant tag
            tenants = {}
            for r in self.waiting:
                t = tenants.setdefault(r.tenant, [0, 0])
                t[0] += 1
            for s in self.slots:
                if s is not None:
                    t = tenants.setdefault(s.req.tenant, [0, 0])
                    t[1] += 1
        self.obs.record_step(
            kind=self._step_kind, duration_s=duration_s,
            n_running=self.n_running, n_waiting=self.n_waiting,
            n_chunking=self.n_chunking,
            blocks_free=self.cache.allocator.n_free,
            blocks_evictable=(self.cache.n_evictable
                              if self.cache.prefix_caching else 0),
            finished=len(self._done_this_step),
            rollback_tokens=rb - self._last_rollback_tokens,
            spec=self.spec.as_dict() if self.spec is not None else None,
            finished_ids=[f.req_id for f in self._done_this_step],
            tenants=tenants)
        self._last_rollback_tokens = rb
        # first-use executable builds are warmup, not throughput: a step
        # that compiled must not enter the sentinel's rate window (same
        # rule the step-gap metric applies)
        compiled = self.n_executables != self._n_exec_last
        self._n_exec_last = self.n_executables
        sen = self.obs.sentinel
        if sen is not None and not compiled and sen.record_step(
                kind=self._step_kind, duration_s=duration_s,
                tokens=self._tokens_this_step):
            # healthy -> degraded transition: attach the numbers that say
            # WHY throughput trails the model (host gap vs pool thrash vs
            # drafter collapse) to the one structured diagnosis line
            gap = self.obs.step_gap.snapshot()
            sen.diagnose({
                "step_gap_mean_ms": round(
                    gap["sum"] / gap["count"] * 1e3, 4) if gap["count"]
                else 0.0,
                "pipeline_flushes": self.obs.pipeline_flushes,
                "preemptions": self.obs.preemptions,
                "ttft_count": self.obs.ttft.count,
                "n_running": self.n_running,
                "n_waiting": self.n_waiting,
            })
        self._sample_hbm()

    def _sample_hbm(self) -> None:
        """One HBM ledger tick: attribute device bytes to named pools and
        feed the steady-state drift detector. The static pools (weights,
        KV pool, cross-KV) are priced once; the dynamic share (resident
        mirror, in-flight lookahead, logical KV usage) is recomputed per
        step. The drift value is the UNEXPLAINED share only — KV bytes no
        live sequence or prefix-cache entry holds (``cache.leaked_bytes``)
        plus device bytes outside every attributed pool — because a
        decoding sequence's held KV grows monotonically by design and
        must never read as a leak."""
        led = self.obs.hbm
        if led is None or self._step_count % self._hbm_every:
            return
        if self._weights_bytes is None:
            try:
                self._weights_bytes = sum(
                    int(getattr(leaf, "nbytes", 0))
                    for leaf in jax.tree_util.tree_leaves(self.params))
            except Exception:
                self._weights_bytes = 0
            self._kv_pool_bytes = self.cache.pool_bytes
            if self._cross_kv is not None:
                self._cross_bytes = sum(
                    int(a["k"].nbytes) + int(a["v"].nbytes)
                    for a in self._cross_kv)
        resident = self._res.device_bytes()
        inflight = 0 if self._pipe is None else self._pipe.device_bytes()
        kv_used = self.cache.used_bytes
        kv_leaked = self.cache.leaked_bytes
        pools = {"weights": self._weights_bytes,
                 "kv_pool": self._kv_pool_bytes,
                 "resident": resident,
                 "inflight": inflight}
        if self._cross_kv is not None:
            pools["cross_kv"] = self._cross_bytes
        stats = None
        dev = self._hbm_dev
        if dev.platform != "cpu":
            # CPU backends report host-heap noise (or nothing) here; the
            # accounted view is the deterministic one for tests/dryruns
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
        stats = stats or {}
        bytes_in_use = stats.get("bytes_in_use")
        drift = kv_leaked
        if bytes_in_use is not None:
            drift += max(0.0, float(bytes_in_use) - sum(pools.values()))
        # host-RAM pools ride the same ledger snapshot as named pools but
        # stay OUT of the attributed device sum (host bytes must not eat
        # HBM headroom): the KV tier's occupancy exports as
        # shai_hbm_host_kv_bytes next to the device pools it backs
        host_pools = None
        if self.cache.tier is not None:
            host_pools = {"host_kv": self.cache.tier.used_bytes}
        led.sample(
            pools=pools,
            composition=(self.n_running, self.n_waiting, self.n_chunking),
            bytes_in_use=bytes_in_use,
            bytes_limit=stats.get("bytes_limit"),
            peak_bytes=stats.get("peak_bytes_in_use"),
            largest_free=stats.get("largest_free_block_bytes"),
            drift_value=drift,
            host_pools=host_pools,
            extra={"kv_used_bytes": kv_used,
                   "kv_leaked_bytes": kv_leaked})

    def _finish(self, fin: Finished) -> None:
        self.finished.append(fin)
        self._done_this_step.append(fin)
        parent = self._rid_parent.pop(fin.req_id, None)
        if parent is not None:
            group = self._fanout_groups.get(parent)
            if group is not None:
                group.discard(fin.req_id)
                if not group:
                    del self._fanout_groups[parent]
        if self.obs.slo is not None:
            self.obs.slo.record_outcome(fin.stop_reason)

    def _mark_first_token(self, req: Request) -> float:
        """TTFT record point (first admission only — a preemption resume is
        not a new first token); returns the timestamp for TPOT's t_first."""
        now = time.monotonic()
        if not req.already_generated and req.t_submit:
            ttft = now - req.t_submit
            self.ttft.record(ttft)
            self.obs.ttft.observe(ttft)
            if self._tenant_seen:
                # per-tenant TTFT attribution: the fairness number the
                # qos fuzz/bench read (a flooded tenant's TTFT must not
                # bleed into the trickle tenant's histogram)
                self.obs.note_tenant_ttft(req.tenant, ttft)
            if self.obs.slo is not None:
                self.obs.slo.record_ttft(ttft)
        if not req.t_first:
            req.t_first = now
        return now

    def _record_tpot(self, s: "_Running") -> None:
        """Per-token decode pace: elapsed spans sample-of-token-1 through
        commit-of-token-n — n decode steps — so divide by n, not n-1."""
        if s.t_first and s.generated:
            tpot = (time.monotonic() - s.t_first) / len(s.generated)
            self.tpot.record(tpot)
            self.obs.tpot.observe(tpot)
            if self.obs.slo is not None:
                self.obs.slo.record_tpot(tpot)

    def _note_admitted(self, req: Request) -> None:
        """Queue-wait record point, at the first admission only (THE hook
        every admission path calls right after taking the request off the
        waiting queue; a preemption resume keeps its original t_admit)."""
        if not req.t_admit:
            req.t_admit = time.monotonic()
            if req.t_submit:
                self.obs.queue_wait.observe(req.t_admit - req.t_submit)

    def _timing_of(self, req: Request, t_first: float = 0.0
                   ) -> Dict[str, float]:
        """Per-phase timeline for a Finished: monotonic stamps plus derived
        queue/prefill/decode durations. Missing stamps fall FORWARD to now,
        collapsing the phases that never ran to zero — a request rejected
        straight from the queue spent its whole life in ``queue_s``, not in
        a decode phase it never reached."""
        now = time.monotonic()
        t_sub = req.t_submit or now
        t_adm = min(req.t_admit or now, now)
        # prefer the request-persisted stamp: a preemption resume's slot
        # t_first is the RESUMED segment's, which would book the first
        # decode segment (and the re-queue wait) under prefill_s
        t_f = min(req.t_first or t_first or now, now)
        t_adm = max(t_sub, t_adm)
        t_f = max(t_adm, t_f)
        out = {
            "t_submit": t_sub, "t_admit": t_adm, "t_first": t_f,
            "t_done": now,
            "queue_s": round(max(0.0, t_adm - t_sub), 6),
            "prefill_s": round(max(0.0, t_f - t_adm), 6),
            "decode_s": round(max(0.0, now - t_f), 6),
            "total_s": round(max(0.0, now - t_sub), 6),
        }
        # sub-phase attribution (fabric probe, kv restore, recompute
        # fallback, pipeline flushes, migration cut): every Finished exit
        # path prices through here, so merging once covers them all
        if req.obs_extra:
            out.update(req.obs_extra)
        return out

    def _start_slot(self, slot: int, req: Request, tok: int) -> None:
        """Seat a fully-prefilled request with its sampled first token."""
        self.slots[slot] = _Running(req, slot, [], pending_token=tok,
                                    t_first=self._mark_first_token(req))

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None) -> List[Finished]:
        """Offline batch: submit all, run to completion, return in order."""
        ids = [self.add_request(p, params) for p in prompts]
        want = set(ids)
        done: Dict[int, Finished] = {}
        while want - set(done):
            for f in self.step():
                done[f.req_id] = f
        return [done[i] for i in ids]

    # -- internals ---------------------------------------------------------

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _need_blocks(self, n_tokens: int) -> int:
        """Optimistic admission cost: prompt blocks plus one decode block of
        headroom, capped at what one sequence can ever use. THE formula —
        every admission path prices through here."""
        return min(self.cache._blocks_needed(n_tokens + self.ecfg.block_size),
                   self.ecfg.blocks_per_seq)

    def _try_reserve(self, req: Request, n_tokens: int) -> bool:
        """Optimistic admission gate for ``self.waiting[0]``: True when the
        pool can hold ``n_tokens`` plus one decode block of headroom. When
        it can't AND nothing is running — the pool is as free as it will
        ever get — the request is rejected-and-finished so the queue can't
        starve (and ``generate()`` can't spin forever)."""
        need = self._need_blocks(n_tokens)
        # chaos site: an injected reservation failure reads as a dry pool,
        # exercising exactly the wait-or-reject ladder a real one takes
        available = (-1 if _faults.get().should_fail(_faults.KV_RESERVE)
                     else self.cache.n_available)
        if need <= available:
            return True
        if not any(s is not None for s in self.slots):
            self.waiting.popleft()
            log.error("rejecting req %d: needs %d blocks, pool max %d",
                      req.req_id, need, self.cache.allocator.n_free)
            self._finish(Finished(
                req.req_id, list(req.already_generated),
                req.orig_n_prompt, "rejected",
                logprobs=(list(req.already_lp)
                          if req.params.logprobs else None),
                timing=self._timing_of(req)))
        return False

    def _admit_one(self) -> None:
        if not self.waiting:
            return
        slot = self._free_slot()
        if slot is None:
            return
        req = self.waiting[0]
        max_text = self.buckets.max - req.prefix_len
        if len(req.prompt_ids) > max_text:
            # preemption re-queues prompt+generated directly and may overflow
            # the largest prefill bucket — keep the tail (matches add_request)
            req.prompt_ids = req.prompt_ids[-max_text:]
        n = req.prefix_len + len(req.prompt_ids)  # total cache tokens
        if not self._try_reserve(req, n):
            return
        self.waiting.popleft()
        self._note_admitted(req)
        P = req.prefix_len
        n_text = len(req.prompt_ids)
        bucket = self.buckets.bucket_for(n)
        alloc = self.cache.admit(req.req_id, n)
        table = jnp.asarray(alloc.table(self.ecfg.blocks_per_seq))[None]
        ids = np.zeros((1, bucket - P), np.int32)
        ids[0, :n_text] = req.prompt_ids
        fn = self._prefill_for(bucket, P)
        args = [self.params, self.cache.kv, jnp.asarray(ids),
                jnp.asarray([n_text], jnp.int32), table]
        if P:
            args.append(jnp.asarray(req.prefix)[None])
        if self._cross_kv is not None:
            args += list(self._set_slot_cross(slot, req))
        with annotate("engine.prefill"):
            self.cache.kv, logits = fn(*args)
        self.obs.count_pad(n, bucket - n, phase="prefill")  # bucket tail
        # no register_prefix here: this path only ever admits prefix/cross
        # (vision-conditioned) requests, whose blocks must NOT
        # content-address by tokens alone — and cross engines disable the
        # cache at construction anyway
        rng = jax.random.fold_in(self._rng, self._step_count * 2 + 1)
        tok = int(self._sample1(
            logits, rng, req.params.temperature, req.params.top_k,
            req.params.top_p)[0])
        self._start_slot(slot, req, tok)
        if req.params.logprobs:
            self._record_admission_lps(logits, [tok],
                                       [(0, self.slots[slot])])

    # -- re-homed plumbing (engine/warm.py, cross.py, logprobs.py) ---------
    # thin delegates so the admission ladder reads unchanged while the
    # mechanics live in their own modules (VERDICT r3 weak #5)

    def warm_executables(self, prefix_lens: Sequence[int] = (0,)) -> int:
        return _warm_mod.warm_executables(self, prefix_lens)

    def _run_warm_calls(self) -> None:
        _warm_mod._run_warm_calls(self)

    def _set_slot_cross(self, slot: int, req: Request):
        return _cross_mod._set_slot_cross(self, slot, req)

    def _cross_zeros(self, K: int):
        return _cross_mod._cross_zeros(self, K)

    def _slot_cross_args(self, slot: int):
        return _cross_mod._slot_cross_args(self, slot)

    @staticmethod
    def _lp_entry(n_top: int, tok: int, tok_lp, top_ids, top_lp) -> Dict:
        return _lp_mod._lp_entry(n_top, tok, tok_lp, top_ids, top_lp)

    def _record_admission_lps(self, logits, toks, rows) -> None:
        _lp_mod._record_admission_lps(self, logits, toks, rows)

    def _admit_batch(self) -> None:
        """Admit up to ``max_prefill_batch`` same-bucket text prompts as ONE
        batched prefill call (VERDICT r2 weak #4: serial prefills made TTFT
        under concurrency pay N x prefill latency)."""
        free = sum(s is None for s in self.slots)
        kmax = min(free, max(1, self.ecfg.max_prefill_batch),
                   self.ecfg.max_num_seqs)
        if not self.waiting or kmax < 1:
            return
        # cap at the largest power of two in the WARMED ladder: padding the
        # group to Kp must never reach an executable warm_executables didn't
        # build (post-ready compiles are the cold-graph-behind-the-LB bug)
        while kmax & (kmax - 1):
            kmax &= kmax - 1
        group: List[Request] = []
        bucket = -1
        first = True
        while self.waiting and len(group) < kmax:
            if not first:
                # every pick beyond the (already scheduled) head is a
                # scheduling decision too: the group ladder must not hand
                # a whole batch to whichever class queued first — a
                # cross-class fair pick whose bucket differs simply
                # flushes the partial group below, fairness over batch
                # packing. No-op (and stride-state-free) with QoS off or
                # a single-class queue.
                self._schedule_head()
            first = False
            req = self.waiting[0]
            if req.prefix is not None or req.cross_states is not None:
                break  # multimodal: handled by the single-seq path next step
            if len(req.prompt_ids) > self.buckets.max:
                # chunk-capable long prompt: NEVER truncate it here — a
                # later step's _admit_long owns it (step() routes there once
                # it reaches the queue head)
                break
            b = self.buckets.bucket_for(len(req.prompt_ids))
            if bucket >= 0 and b != bucket:
                break  # different bucket: next step's batch
            n = len(req.prompt_ids)
            if group:
                if self._need_blocks(n) > self.cache.n_available:
                    break  # partial group admitted — flush it, retry next step
            elif not self._try_reserve(req, n):
                if self.waiting and self.waiting[0] is req:
                    break  # pool busy — retry next step
                continue   # rejected-and-finished; consider the next head
            bucket = b
            self.waiting.popleft()
            self._note_admitted(req)
            self.cache.admit(req.req_id, n)
            group.append(req)
        if not group:
            return
        K = len(group)
        Kp = 1 << (K - 1).bit_length()  # executable batch: power of two
        M = self.ecfg.blocks_per_seq
        ids = np.zeros((Kp, bucket), np.int32)
        n_text = np.ones((Kp,), np.int32)     # dummy rows: 1 masked token
        tables = np.zeros((Kp, M), np.int32)  # dummy rows: null block 0
        temp = np.ones((Kp,), np.float32)
        topk = np.zeros((Kp,), np.int32)
        topp = np.ones((Kp,), np.float32)
        for i, req in enumerate(group):
            ids[i, :len(req.prompt_ids)] = req.prompt_ids
            n_text[i] = len(req.prompt_ids)
            tables[i] = self.cache.seq(req.req_id).table(M)
            temp[i] = req.params.temperature
            topk[i] = req.params.top_k
            topp[i] = req.params.top_p
        fn = self._prefill_for(bucket, 0, Kp)
        args = [self.params, self.cache.kv, jnp.asarray(ids),
                jnp.asarray(n_text), jnp.asarray(tables)]
        if self._cross_kv is not None:  # text-only rows through a cross model
            args += [self._cross_zeros(Kp), jnp.zeros((Kp,), jnp.float32),
                     jnp.full((Kp,), max(self.cross_seq_len, 1), jnp.int32)]
        with annotate("engine.prefill"):
            self.cache.kv, logits = fn(*args)
        real = sum(len(r.prompt_ids) for r in group)
        self.obs.count_pad(real, Kp * bucket - real,
                           phase="prefill")  # bucket + batch pad
        for req in group:  # batch rows are always plain text
            self.cache.register_prefix(req.prompt_ids,
                                       self.cache.seq(req.req_id).blocks)
        rng = jax.random.fold_in(self._rng, self._step_count * 2 + 1)
        toks = np.asarray(self._sample1(
            logits, rng, jnp.asarray(temp), jnp.asarray(topk),
            jnp.asarray(topp)))
        lp_rows = []
        for i, req in enumerate(group):
            slot = self._free_slot()
            self._has_image[slot] = 0.0
            self._start_slot(slot, req, int(toks[i]))
            if req.params.logprobs:
                lp_rows.append((i, self.slots[slot]))
        if lp_rows:
            self._record_admission_lps(logits, [int(t) for t in toks],
                                       lp_rows)

    def _fabric_probe(self, req, hashes: List[int],
                      from_block: int) -> int:
        """The admission ladder's peer-probe rung (kvnet.directory):
        pull the prompt's leading KV run from a fleet holder into the
        host tier so ordinary warm admission takes it from there. Priced
        BEFORE any network work: no holders (the cold fleet) costs
        nothing, the probe budget is capped at the recompute time it
        could save (PERF_MODEL via the sentinel), and a deadline with
        less headroom than those savings skips the rung outright.
        Returns blocks fetched (0 = recompute); never raises."""
        fab = self._kvfabric
        if fab is None or from_block >= len(hashes):
            return 0
        want = hashes[from_block:]
        holders = list(req.kv_holders) or fab.holders_for(want[0])
        if not holders:
            return 0
        budget = fab.client.timeout_s
        rate = float(getattr(self.obs.sentinel, "projected_per_s", 0.0)
                     or 0.0)
        if rate > 0.0:
            savings = len(want) * self.ecfg.block_size / rate
            budget = min(budget, savings)
            if req.deadline_at and req.deadline_at - time.monotonic() \
                    < savings:
                return 0  # priced out: the headroom belongs to recompute
        elif req.deadline_at:
            budget = min(budget, req.deadline_at - time.monotonic())
        t0 = time.monotonic()
        got = fab.probe(want, holders, budget,
                        traceparent=req.traceparent or None)
        req.obs_extra["t_fabric"] = t0
        req.obs_extra["fabric_probe_s"] = round(time.monotonic() - t0, 6)
        req.obs_extra["fabric_blocks"] = float(got)
        return got

    def _admit_cached(self) -> bool:
        """Admit the head request reusing its cached prefix blocks: incref
        the shared blocks, run ONE continuation chunk over just the
        uncached remainder, and register the result. Returns False when the
        cache offers no usable (warm-start-aligned) benefit — the caller
        falls through to the normal admission paths."""
        req = self.waiting[0]
        n_total = len(req.prompt_ids)
        if n_total <= self.ecfg.block_size:
            return False  # no full block to share
        if self._fused and self._kv_quant:
            # int8 pools re-quantize a written block over EVERYTHING in it:
            # the fused C-sized window writes pad garbage past the cached
            # remainder that the laddered chunk_bucket never touched, so
            # the tail block's scale (and every real token quantized under
            # it) would diverge from the oracle — fall through to plain
            # admission, which prefills from scratch and stays exact
            return False
        slot = self._free_slot()
        if slot is None:
            # probe NOTHING while blocked on a slot: a waiting request
            # retries every step, and per-step probes would churn both
            # LRUs and inflate the tier's hit counters with non-admissions
            return False
        # the chain hash is pure-Python token hashing — compute it ONCE
        # and share it across the device walk, tier probe, and restore
        hashes = self.cache.prefix_hashes(req.prompt_ids)
        cached = self.cache.cached_prefix(req.prompt_ids, hashes=hashes)
        # host-tier fall-through: blocks the device cache evicted (or a
        # preemption demoted) may still be host-resident — they extend the
        # warm run the start alignment below is computed from
        n_tier = self.cache.tier_prefix_len(hashes, len(cached))
        start = self._cached_start_for(
            n_total, (len(cached) + n_tier) * self.ecfg.block_size)
        if start == 0 and self._kvfabric is not None:
            # third rung (KV fabric): device AND host tier came up cold —
            # a fleet holder may still have the run. The probe publishes
            # into the host tier, so on success the ordinary tier-restore
            # path below admits against it unchanged.
            if self._fabric_probe(req, hashes, len(cached)) > 0:
                n_tier = self.cache.tier_prefix_len(hashes, len(cached))
                start = self._cached_start_for(
                    n_total, (len(cached) + n_tier) * self.ecfg.block_size)
        if start == 0:
            return False
        chunk_bucket = self._cached_chunk_bucket(n_total - start)
        sb = start // self.ecfg.block_size
        if start + chunk_bucket > self.ecfg.max_model_len:
            return False  # chunk executable would overrun blocks_per_seq
        if self._cont_cold(sb, chunk_bucket):
            return False  # post-ready compiles are the cold-graph bug
        take = max(0, sb - len(cached))
        need_new = self._need_blocks(n_total) - sb
        # conservative: pinning the reused blocks removes up to sb blocks
        # from the evictable supply n_available counts, and the restore
        # itself consumes `take` fresh blocks before admission even starts
        if need_new + take > self.cache.n_available - sb:
            return False  # normal paths own reject-vs-wait semantics
        if take:
            # the restore scatter donates the device pool buffers: retire
            # any in-flight lookahead FIRST so the async discipline stays
            # token-exact (no-op in lock-step / already-flushed steps)
            self._flush_pipeline("kvtier", req=req)
            t0 = time.monotonic()
            n_before = len(cached)
            cached = cached + self.cache.restore_prefix(
                hashes, len(cached), take, pin=cached)
            req.obs_extra["t_kv_restore"] = t0
            req.obs_extra["kv_restore_s"] = round(
                time.monotonic() - t0, 6)
            req.obs_extra["kv_restore_blocks"] = float(
                len(cached) - n_before)
            if len(cached) < sb:
                # tier shortfall (raced host eviction, transfer failure):
                # degrade to the blocks we DID land — they are device-
                # cached now — and re-derive the warm start from them;
                # recompute covers the rest, the request never fails
                start = self._cached_start_for(
                    n_total, len(cached) * self.ecfg.block_size)
                if start == 0:
                    return False
                chunk_bucket = self._cached_chunk_bucket(n_total - start)
                sb = start // self.ecfg.block_size
                if start + chunk_bucket > self.ecfg.max_model_len:
                    return False
                if self._cont_cold(sb, chunk_bucket):
                    return False
        self.waiting.popleft()
        try:
            alloc = self.cache.admit(req.req_id, n_total,
                                     reuse_blocks=cached[:sb])
        except MemoryError:
            self.waiting.appendleft(req)
            return False  # let the normal paths wait-or-reject
        self._note_admitted(req)
        # recompute fallback: the prompt suffix past the warm start is
        # re-prefilled, not restored — the trace's prefill span carries it
        req.obs_extra["recompute_tokens"] = float(n_total - start)
        table = jnp.asarray(alloc.table(self.ecfg.blocks_per_seq))[None]
        n = n_total - start
        ids = np.zeros((1, chunk_bucket), np.int32)
        ids[0, :n] = req.prompt_ids[start:]
        if self._fused:
            # a deferred window must not reorder behind this admission's
            # own window (the admission may reuse blocks the deferred
            # chunk is still due to write)
            self._flush_chunk()
            logits = self._fused_chunk_call(
                jnp.asarray(ids), jnp.asarray([n], jnp.int32), table,
                jnp.asarray([start], jnp.int32))
        else:
            fn = self._cont_for(sb, chunk_bucket)
            with annotate("engine.prefill"):
                self.cache.kv, logits = fn(self.params, self.cache.kv,
                                           jnp.asarray(ids),
                                           jnp.asarray([n], jnp.int32),
                                           table, *self._cont_args(start))
        self.obs.count_pad(n, chunk_bucket - n,
                           phase="prefill")  # chunk bucket tail
        self.cache.register_prefix(req.prompt_ids, alloc.blocks)
        rng = jax.random.fold_in(self._rng, self._step_count * 2 + 1)
        tok = int(self._sample1(
            logits, rng, req.params.temperature, req.params.top_k,
            req.params.top_p)[0])
        self._has_image[slot] = 0.0
        self._start_slot(slot, req, tok)
        if req.params.logprobs:
            self._record_admission_lps(logits, [tok],
                                       [(0, self.slots[slot])])
        return True

    def _admit_fanout(self) -> bool:
        """Admit an n>1 sampling fan-out group (SHAI_KV_COW) as ONE shared
        prefill: the group's prompt prefills once, every sibling beyond the
        first forks the prompt blocks copy-on-write (``cache.
        fork_sequence`` — the first divergent decode write pays one block
        copy), and all K rows sample their first token from the SAME tiled
        logits row under the batch-admission fold. Token-exact vs K
        independent admissions because ``sample_logits``' per-row gumbel
        depends only on the row index — tiling the one logits row to the
        batch layout reproduces exactly what K identical prompt rows of a
        Kp-batch prefill would have sampled. Returns False with NOTHING
        consumed when the group isn't fully queued or doesn't fit — the
        siblings then admit independently through the normal ladder
        (correct, just without the sharing)."""
        head = self.waiting[0]
        parent = self._rid_parent.get(head.req_id)
        if parent is None:
            return False
        group = [r for r in self.waiting
                 if self._rid_parent.get(r.req_id) == parent]
        if len(group) < 2 or group[0] is not head:
            return False  # partial group (or mid-requeue): normal ladder
        n = len(head.prompt_ids)
        if n > self.buckets.max:
            return False  # chunk-length prompts fan out independently
        if any(r.prompt_ids != head.prompt_ids or r.prefix is not None
               or r.cross_states is not None or r.already_generated
               for r in group):
            # a preempted/migrated sibling carries generated suffix — the
            # group no longer shares one prompt; admit independently
            return False
        K = len(group)
        if sum(s is None for s in self.slots) < K:
            return False  # all-or-nothing: the group decodes together
        # price the group before touching anything: one prompt's blocks
        # plus one CoW-copy block of headroom per sibling (each fork's
        # first divergent write may need its private tail copy)
        if self._need_blocks(n) + K > self.cache.n_available:
            return False
        bucket = self.buckets.bucket_for(n)
        Kp = 1 << (K - 1).bit_length()
        if self._warmed and (bucket, 0, 1) not in self._prefill:
            return False  # post-ready compiles are the cold-graph bug
        try:
            alloc = self.cache.admit(head.req_id, n)
        except MemoryError:
            return False  # raced estimate: normal paths own wait-or-reject
        # the all-or-nothing point is passed — dequeue the WHOLE group (by
        # identity: fairness rotation may have interleaved other requests)
        members = {id(r) for r in group}
        self.waiting = deque(r for r in self.waiting
                             if id(r) not in members)
        for r in group:
            self._note_admitted(r)
        for r in group[1:]:
            self.cache.fork_sequence(head.req_id, r.req_id)
        table = jnp.asarray(alloc.table(self.ecfg.blocks_per_seq))[None]
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = head.prompt_ids
        fn = self._prefill_for(bucket, 0, 1)
        with annotate("engine.prefill"):
            self.cache.kv, logits = fn(self.params, self.cache.kv,
                                       jnp.asarray(ids),
                                       jnp.asarray([n], jnp.int32), table)
        self.obs.count_pad(n, bucket - n, phase="prefill")
        self.cache.register_prefix(head.prompt_ids, alloc.blocks)
        temp = np.ones((Kp,), np.float32)
        topk = np.zeros((Kp,), np.int32)
        topp = np.ones((Kp,), np.float32)
        for i, r in enumerate(group):
            temp[i] = r.params.temperature
            topk[i] = r.params.top_k
            topp[i] = r.params.top_p
        tiled = jnp.broadcast_to(logits[0], (Kp,) + logits.shape[1:])
        rng = jax.random.fold_in(self._rng, self._step_count * 2 + 1)
        toks = np.asarray(self._sample1(
            tiled, rng, jnp.asarray(temp), jnp.asarray(topk),
            jnp.asarray(topp)))
        lp_rows = []
        for i, r in enumerate(group):
            slot = self._free_slot()
            self._has_image[slot] = 0.0
            self._start_slot(slot, r, int(toks[i]))
            if r.params.logprobs:
                lp_rows.append((i, self.slots[slot]))
        if lp_rows:
            self._record_admission_lps(tiled, [int(t) for t in toks],
                                       lp_rows)
        return True

    def _admit_long(self) -> None:
        """Admit a prompt longer than the largest prefill bucket: allocate
        its full block run, encode the first bucket-sized chunk now, and
        leave a cursor for ``_continue_prefill`` to advance one chunk per
        step (decode keeps running between chunks). At most one sequence
        chunks at a time — a second long prompt waits."""
        if not self.waiting:
            return
        slot = self._free_slot()
        if slot is None:
            return
        req = self.waiting[0]
        if len(req.prompt_ids) > self._chunk_cap:
            # preemption re-queues prompt+generated directly, which may
            # exceed the chunkable cap — keep the tail (matches add_request)
            req.prompt_ids = req.prompt_ids[-self._chunk_cap:]
        n_total = len(req.prompt_ids)
        C = self.buckets.max
        if n_total <= C:
            # truncation brought it back inside one bucket — normal path
            if req.cross_states is not None:
                self._admit_one()
            else:
                self._admit_batch()
            return
        if not self._try_reserve(req, n_total):
            return
        self.waiting.popleft()
        self._note_admitted(req)
        self.cache.admit(req.req_id, n_total)
        table = jnp.asarray(
            self.cache.seq(req.req_id).table(self.ecfg.blocks_per_seq))[None]
        ids = np.asarray(req.prompt_ids[:C], np.int32)[None]
        fn = self._prefill_for(C, 0, 1)
        args = [self.params, self.cache.kv, jnp.asarray(ids),
                jnp.asarray([C], jnp.int32), table]
        self._has_image[slot] = 0.0
        if self._cross_kv is not None:
            # seat the vision states (or the text-only gate-off) in the slot
            # buffers once; every chunk and decode step reads them from there
            args += list(self._set_slot_cross(slot, req))
        with annotate("engine.prefill"):
            self.cache.kv, _ = fn(*args)
        # the first chunk's full blocks are final (prefill never rewrites
        # them): register them NOW — a second identical long prompt, or
        # this one resuming after preemption, shares them without waiting
        # out the whole chunk ladder (register_prefix no-ops for cross
        # engines, whose cache is disabled at construction)
        self.cache.register_prefix(req.prompt_ids[:C],
                                   self.cache.seq(req.req_id).blocks)
        self.slots[slot] = _Running(req, slot, [], pending_token=-1,
                                    prefill_cursor=C)

    def _continue_prefill(self, s: _Running) -> None:
        """Encode the next chunk of a mid-prefill slot; on the final chunk,
        sample the first token and join the decode batch."""
        req = s.req
        start = s.prefill_cursor
        C = self.buckets.max
        chunk = req.prompt_ids[start:start + C]
        n = len(chunk)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = chunk
        table = jnp.asarray(
            self.cache.seq(req.req_id).table(self.ecfg.blocks_per_seq))[None]
        final = start + n >= len(req.prompt_ids)
        if self._fused and not final:
            # intermediate chunk: DEFER the window — it rides this step's
            # decode dispatch as the fused executable's chunk section (one
            # dispatch where the ladder paid two; THE interference win).
            # Its logits are discarded exactly as the laddered oracle
            # discards intermediate-chunk logits; registration and the
            # cursor advance keep the oracle's timing.
            self._flush_chunk()  # never stack two windows
            self._pending_chunk = (jnp.asarray(ids),
                                   jnp.asarray([n], jnp.int32), table,
                                   jnp.asarray([start], jnp.int32))
            self.obs.count_pad(n, C - n, phase="chunk")
            self.cache.register_prefix(
                req.prompt_ids[:start + n],
                self.cache.seq(req.req_id).blocks)
            s.prefill_cursor = start + C
            return
        if self._fused:
            # final chunk: its sampled token joins THIS step's decode
            # batch — that circular dependency forbids sharing the decode
            # dispatch, so the window runs chunk-only (null decode rows);
            # 2 dispatches, the laddered oracle's own structure
            self._flush_chunk()
            logits = self._fused_chunk_call(
                jnp.asarray(ids), jnp.asarray([n], jnp.int32), table,
                jnp.asarray([start], jnp.int32))
        else:
            fn = self._cont_for(start // self.ecfg.block_size)
            args = [self.params, self.cache.kv, jnp.asarray(ids),
                    jnp.asarray([n], jnp.int32), table]
            args += self._cont_args(start)  # ragged: start rides as data
            if self._cross_kv is not None:
                args += list(self._slot_cross_args(s.slot))
            with annotate("engine.prefill"):
                self.cache.kv, logits = fn(*args)
        self.obs.count_pad(n, C - n, phase="chunk")  # final-chunk tail
        if final:
            self.cache.register_prefix(
                req.prompt_ids, self.cache.seq(req.req_id).blocks)
            # own stream: admission may also sample this step (fold 2s+1),
            # and decode uses fold 2s — a double fold can't collide with
            # either single-fold stream
            rng = jax.random.fold_in(
                jax.random.fold_in(self._rng, self._step_count), 3)
            tok = int(self._sample1(
                logits, rng, req.params.temperature, req.params.top_k,
                req.params.top_p)[0])
            s.pending_token = tok
            s.prefill_cursor = None
            s.t_first = self._mark_first_token(req)
            if req.params.logprobs:
                self._record_admission_lps(logits, [tok], [(0, s)])
        else:
            # intermediate chunk: its full blocks are final too — publish
            # them per chunk instead of only at prompt completion (the
            # chunked path previously registered nothing until the last
            # chunk, so identical long prompts paid the full ladder twice)
            self.cache.register_prefix(
                req.prompt_ids[:start + n],
                self.cache.seq(req.req_id).blocks)
            s.prefill_cursor = start + C

    def _cont_for(self, start_blocks: int, bucket: Optional[int] = None):
        from .runner import make_prefill_cont

        bucket = self.buckets.max if bucket is None else bucket
        if self._ragged:
            # ONE dynamic-start executable per chunk bucket replaces the
            # whole one-per-start continuation ladder; callers append the
            # start array to the call args (_cont_args)
            key = ("rcont", bucket)
            if key not in self._prefill:
                _faults.get().raise_at(_faults.COMPILE)
                if self._warmed:
                    self.obs.count_recompile("prefill_cont")
                self._prefill[key] = make_prefill_cont(
                    self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                    bucket, shardings=self.shardings,
                    kv_quant=self._kv_quant, ragged=True)
            return self._prefill[key]
        key = ("cont", start_blocks, bucket)
        if key not in self._prefill:
            _faults.get().raise_at(_faults.COMPILE)
            if self._warmed:
                # post-warm compile == a shape escaped the warmed closed
                # set (the cold-graph-behind-the-LB signal)
                self.obs.count_recompile("prefill_cont")
            self._prefill[key] = make_prefill_cont(
                self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                bucket, start_blocks, shardings=self.shardings,
                kv_quant=self._kv_quant)
        return self._prefill[key]

    def _cont_key(self, start_blocks: int, bucket: int):
        """The warm-ladder key a continuation dispatch will resolve to —
        the post-ready compile guards in cached admission check THIS, so
        the ragged ladder's (start-free) keys gate correctly."""
        if self._ragged:
            return ("rcont", bucket)
        return ("cont", start_blocks, bucket)

    def _cached_chunk_bucket(self, remainder: int) -> int:
        """Window the cached-admission continuation dispatches: the fused
        step's chunk section is pinned to the largest prefill bucket (one
        executable per batch bucket — sizing it per remainder would grow
        the ladder back); the laddered engine keeps the smallest covering
        bucket."""
        if self._fused:
            return self.buckets.max
        return self.buckets.bucket_for(remainder)

    def _cont_cold(self, sb: int, chunk_bucket: int) -> bool:
        """Post-ready compile guard for a continuation dispatch: True when
        the executable it would resolve to was never warmed (the cold-
        graph-behind-the-LB bug). The fused step dispatches chunk-only
        windows through the bb=1 fused executable."""
        if not self._warmed:
            return False
        if self._fused:
            return 1 not in self._fused_fns
        return self._cont_key(sb, chunk_bucket) not in self._prefill

    def _cont_args(self, start: int) -> list:
        """Trailing args a continuation executable takes beyond
        ``(params, kv, ids, n_text, block_tables)``: the ragged variant
        carries the chunk start as DATA."""
        if self._ragged:
            return [jnp.asarray([start], jnp.int32)]
        return []

    def _cached_starts(self) -> List[int]:
        """THE closed set of continuation starts (token units) — both the
        warm ladder and cached admission price from this one list: every
        prefill bucket plus every multiple of the largest bucket."""
        C = self.buckets.max
        starts = set(self.buckets.buckets)
        s = C
        while s + 1 < self.ecfg.max_model_len:
            starts.add(s)
            s += C
        return sorted(starts)

    def _cached_start_for(self, n_total: int, cached_tokens: int) -> int:
        """Largest warm continuation start covered by the cached prefix and
        leaving a remainder that fits ONE chunk executable; 0 = no benefit."""
        C = self.buckets.max
        best = 0
        for s in self._cached_starts():
            if (s <= cached_tokens and s < n_total
                    and n_total - s <= C and s > best):
                best = s
        return best

    def _prefill_for(self, bucket: int, prefix_len: int = 0, n_seqs: int = 1):
        key = (bucket, prefix_len, n_seqs)
        if key not in self._prefill:
            # chaos site: executable-factory compile failure
            _faults.get().raise_at(_faults.COMPILE)
            if self._warmed:
                self.obs.count_recompile("prefill")
            self._prefill[key] = make_prefill(
                self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                bucket, prefix_len=prefix_len, n_seqs=n_seqs,
                shardings=self.shardings, kv_quant=self._kv_quant)
        return self._prefill[key]

    def _batch_bucket(self, n_active: int) -> int:
        """Smallest power-of-two batch covering ``n_active`` (occupancy
        bucketing: a lone sequence must not pay for a full idle batch —
        VERDICT r2 weak #3)."""
        b = 1
        while b < n_active:
            b *= 2
        return min(b, self.ecfg.max_num_seqs)

    def _decode_for(self, m_blocks: int, n_active: int = -1):
        """Decode executable for the smallest (context, batch) buckets
        covering the running set."""
        if self._fused:
            return self._fused_decode_for(n_active)
        m = next(b for b in self._ctx_buckets if b >= m_blocks)
        bb = (self.ecfg.max_num_seqs if n_active < 0
              else self._batch_bucket(n_active))
        key = (m, bb)
        if key not in self._decode_fns:
            _faults.get().raise_at(_faults.COMPILE)
            if self._warmed:
                self.obs.count_recompile("decode")
            # async engines compile the feedback variant (returns pos+1,
            # donates the position buffer) into the SAME (ctx, batch)
            # ladder — one executable per key either way
            self._decode_fns[key] = make_decode(
                self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                bb, ctx_blocks=m, shardings=self.shardings,
                feedback=self._async, ragged=self._ragged,
                kv_quant=self._kv_quant)
        return bb, self._decode_fns[key]

    # -- fused mixed-phase step (SHAI_FUSED_STEP) --------------------------

    def _fused_for(self, n_active: int = -1):
        """Fused mixed-phase executable for the smallest batch bucket
        covering the running set: the decode rows plus ONE continuation-
        chunk window in a single ragged dispatch (runner.make_fused_step).
        Mirrors ``_decode_for``'s ladder discipline — one entry per batch
        bucket; the context ladder is already collapsed by ragged, and the
        chunk window is pinned to the largest prefill bucket."""
        bb = (self.ecfg.max_num_seqs if n_active < 0
              else self._batch_bucket(n_active))
        if bb not in self._fused_fns:
            from .runner import make_fused_step

            _faults.get().raise_at(_faults.COMPILE)
            if self._warmed:
                self.obs.count_recompile("fused")
            self._fused_fns[bb] = make_fused_step(
                self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                bb, self.buckets.max, shardings=self.shardings,
                feedback=self._async, kv_quant=self._kv_quant)
        return bb, self._fused_fns[bb]

    def _fused_decode_for(self, n_active: int = -1):
        """The decode-shaped view of the fused executable: append the
        pending (or null) chunk-window args and drop the trailing chunk
        logits, so ``_decode_for``'s callers dispatch it unchanged. An
        intermediate chunk deferred by ``_continue_prefill`` rides THIS
        dispatch; its logits are discarded exactly as the laddered oracle
        discards intermediate-chunk logits."""
        bb, fused = self._fused_for(n_active)

        def decode(*args):
            out = fused(*args, *self._take_chunk_args())
            return out[:-1]

        return bb, decode

    def _null_chunk_args(self) -> list:
        """Device-cached null chunk window: zero ids over null block 0
        with ``n_text=1`` — a pure-decode fused dispatch carries it so
        the executable signature never changes. Its writes land in
        reserved block 0, outside every live window; nothing reads them."""
        if self._null_chunk is None:
            self._null_chunk = [
                jnp.zeros((1, self.buckets.max), jnp.int32),
                jnp.ones((1,), jnp.int32),
                jnp.zeros((1, self.ecfg.blocks_per_seq), jnp.int32),
                jnp.zeros((1,), jnp.int32)]
        return self._null_chunk

    def _take_chunk_args(self) -> list:
        """Consume the deferred continuation window (or hand out nulls)."""
        pc, self._pending_chunk = self._pending_chunk, None
        if pc is None:
            return self._null_chunk_args()
        return list(pc)

    def _fused_chunk_call(self, ids_dev, n_dev, table, start_dev):
        """Chunk-only fused dispatch (bb=1): the decode section runs null
        rows (active all-false; their block-0 writes are harmless) while
        the chunk window does the real work. Used for final chunks and
        cached admission, whose sampled token feeds the SAME step's decode
        batch — a circular dependency that forbids sharing that dispatch.
        Returns the chunk's last-real-position logits ``[1, V]``. The
        decode tokens/pos are SEPARATE zero buffers: the feedback variant
        donates the position argument, so aliasing them would donate the
        token buffer too."""
        _, fused = self._fused_for(1)
        args = [self.params, self.cache.kv,
                jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
                jnp.zeros((1, self.ecfg.blocks_per_seq), jnp.int32),
                jnp.zeros((1,), bool), self._rng,
                jnp.ones((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
                jnp.ones((1,), jnp.float32),
                ids_dev, n_dev, table, start_dev]
        with annotate("engine.prefill"):
            out = fused(*args)
        self.cache.kv = out[0]
        return out[-1]

    def _flush_chunk(self) -> None:
        """Dispatch any deferred continuation window NOW (no-op when
        none): paths that skip the decode dispatch — or would reorder KV
        writes around it — must not leave a window parked."""
        if self._pending_chunk is not None:
            self._fused_chunk_call(*self._take_chunk_args())

    def _verify_for(self, m_blocks: int, n_active: int = -1):
        """Speculative verify executable for the smallest (context, batch)
        buckets covering the running set — the same dispatch rule as
        ``_decode_for``, k+1 scored positions per sequence."""
        from .runner import make_verify

        m = next(b for b in self._ctx_buckets if b >= m_blocks)
        bb = (self.ecfg.max_num_seqs if n_active < 0
              else self._batch_bucket(n_active))
        key = (m, bb)
        if key not in self._verify_fns:
            _faults.get().raise_at(_faults.COMPILE)
            if self._warmed:
                self.obs.count_recompile("verify")
            self._verify_fns[key] = make_verify(
                self.cfg, self.ecfg.block_size, self.ecfg.blocks_per_seq,
                bb, self.ecfg.num_speculative_tokens, ctx_blocks=m,
                shardings=self.shardings, ragged=self._ragged,
                kv_quant=self._kv_quant)
        return bb, self._verify_fns[key]

    @property
    def n_executables(self) -> int:
        return (len(self._prefill) + len(self._decode_fns)
                + len(self._verify_fns) + len(self._fused_fns))

    def _preempt_lowest(self) -> None:
        """Recompute-preempt the lowest-priority, most recently admitted
        sequence: under pool pressure the low class pays first (kvtier
        keeps the eviction a demotion, so the victim resumes from restored
        KV, not recompute). Priority weighs in ONLY under SHAI_QOS: with
        QoS off the key is exactly the original most-recent-req_id rule —
        an unauthenticated X-SHAI-Priority header must not become a free
        anti-preemption lever on a FIFO pod (and the differential oracle
        stays exact even for tagged traffic)."""
        # defensive: preemption streams/commits the victim's pending token,
        # so the host mirror must be current (the event paths flush before
        # ever reaching the allocator; this covers any future caller)
        self._flush_pipeline("preempt")
        victims = [s for s in self.slots if s is not None]
        if self._sched is not None:
            victim = max(victims,
                         key=lambda s: (s.req.priority, s.req.req_id))
        else:
            victim = max(victims, key=lambda s: s.req.req_id)
        log.warning("preempting seq %d (block pool exhausted)", victim.req.req_id)
        self.obs.count_preemption()
        if (self.cache.tier is not None and victim.req.prefix is None
                and victim.req.cross_states is None):
            # demotion, not deletion: publish the victim's full blocks to
            # the prefix cache before release — re-admission reuses them
            # while they survive on device, and pool pressure demotes them
            # to the host tier through the eviction hook; the resumed
            # sequence restores KV instead of recomputing it. (KV exists
            # for prompt+generated only — the pending token's write lands
            # with the NEXT dispatch, which this victim never runs.)
            kv_tokens = (victim.req.prompt_ids[:victim.prefill_cursor]
                         if victim.prefill_cursor is not None
                         else victim.req.prompt_ids + victim.generated)
            self.cache.offload_preempt(kv_tokens, victim.req.req_id)
        self._release_slot(victim)
        if victim.prefill_cursor is not None:
            # mid-prefill victim: nothing generated — the prompt simply
            # re-queues and re-chunks from the start when blocks free up
            self.waiting.appendleft(victim.req)
            return
        # generated + pending tokens become cache prompt suffix, but stay in
        # the client-visible output via already_generated; budget shrinks by
        # what is already committed (pending included — it was sampled)
        committed = victim.generated + [victim.pending_token]
        p = victim.req.params
        if (victim.req.on_token is not None
                and victim.pending_token != p.eos_id):
            # the pending token was sampled but never appended — it WILL be
            # in the final output (as prompt suffix), so stream it now to
            # keep the exactly-once-per-output-token invariant
            victim.req.on_token(victim.pending_token)
        emitted = victim.req.already_generated + committed
        if victim.pending_token == p.eos_id or len(committed) >= p.max_new_tokens:
            self._record_tpot(victim)
            # nothing left to resume — finish right here
            lps = None
            if p.logprobs:
                lps = victim.req.already_lp + victim.lps
            if emitted and emitted[-1] == p.eos_id:
                emitted = emitted[:-1]
                if lps:
                    lps = lps[:-1]
                reason = "eos"
            else:
                reason = "length"
            self._finish(Finished(
                victim.req.req_id, emitted, victim.req.orig_n_prompt, reason,
                logprobs=lps, timing=self._timing_of(victim.req,
                                                     victim.t_first)))
            return
        # record this decode segment's pace before the slot state is lost —
        # preemption happens at peak load, exactly what TPOT must show
        self._record_tpot(victim)
        params = dataclasses.replace(
            p, max_new_tokens=p.max_new_tokens - len(committed))
        self.waiting.appendleft(Request(
            victim.req.req_id,
            victim.req.prompt_ids + committed,
            params,
            prefix=victim.req.prefix,
            cross_states=victim.req.cross_states,
            cross_len=victim.req.cross_len,
            already_generated=emitted,
            orig_n_prompt=victim.req.orig_n_prompt,
            on_token=victim.req.on_token,
            deadline_at=victim.req.deadline_at,
            t_submit=victim.req.t_submit,
            t_admit=victim.req.t_admit,
            t_first=victim.req.t_first,
            idem_key=victim.req.idem_key,
            already_lp=(victim.req.already_lp + victim.lps
                        if p.logprobs else [])))

    def _grow_running(self, n_ext_for) -> None:
        """Reserve ``n_ext_for(slot)`` cache tokens for every decoding slot,
        recompute-preempting on pool exhaustion (never down to zero running
        sequences) — THE reservation step of both decode paths."""
        for s in list(self.slots):
            if s is None or s.prefill_cursor is not None:
                continue  # mid-prefill slots neither grow nor decode yet
            if self.slots[s.slot] is not s:
                # an EARLIER iteration's pool pressure preempted this slot:
                # its sequence is already released — extending it would
                # KeyError and kill the whole engine step
                continue
            n_ext = n_ext_for(s)
            while True:
                try:
                    self.cache.extend(s.req.req_id, n_ext)
                    break
                except MemoryError:
                    if sum(x is not None for x in self.slots) <= 1:
                        raise  # one seq must always fit: config error
                    self._preempt_lowest()
                    if self.slots[s.slot] is not s:
                        break  # s itself was preempted

    def _note_dispatch_pad(self, running, Bb: int,
                           rows_per_seq: int = 1) -> None:
        """Pad-waste accounting for ONE decode/verify dispatch: ``real``
        is the context tokens the rows actually hold, ``padded`` the token
        slots the executable walks beyond them — batch pad rows plus the
        context window past each row's live tokens. Bucketed dispatch
        walks the dispatched context bucket for EVERY row; the ragged
        kernel walks each row's own blocks (partial-tail slots only).
        ``rows_per_seq``: the verify executable flattens ``k + 1`` query
        rows per sequence, each walking the window — both sides scale.
        Exported as ``shai_engine_pad_tokens_total``/``pad_fraction`` so
        the ragged win is measurable on a live pod — and a ladder growing
        back is visible. Pure host arithmetic (hot-path safe)."""
        bs = self.ecfg.block_size
        real = 0
        walked = 0
        if self._ragged:
            for s in running:
                n = self.cache.seq(s.req.req_id).n_tokens
                real += n
                walked += self.cache._blocks_needed(n) * bs
            walked += (Bb - len(running)) * bs  # pad rows walk one block
        else:
            m_blocks = 1
            for s in running:
                n = self.cache.seq(s.req.req_id).n_tokens
                real += n
                m_blocks = max(m_blocks, self.cache._blocks_needed(n))
            m = next(b for b in self._ctx_buckets if b >= m_blocks)
            walked = Bb * m * bs
        self.obs.count_pad(real * rows_per_seq,
                           (walked - real) * rows_per_seq,
                           phase="verify" if rows_per_seq > 1 else "decode")

    def _running_slots(self) -> List["_Running"]:
        return [s for s in self.slots
                if s is not None and s.prefill_cursor is None]

    def _max_ctx_blocks(self, running) -> int:
        m_blocks = 1
        for s in running:
            m_blocks = max(m_blocks, self.cache._blocks_needed(
                self.cache.seq(s.req.req_id).n_tokens))
        return m_blocks

    def _marshal_running(self, running, Bb: int) -> Dict[str, np.ndarray]:
        """Compact the active slots into the first ``len(running)`` batch
        rows — the pool is slot-agnostic (block tables are data), so only
        the batch view compacts; padding rows carry null tables and write
        harmlessly into reserved block 0. Shared by decode and verify;
        callers add their own token/position arrays."""
        M = self.ecfg.blocks_per_seq
        a = {
            "tables": np.zeros((Bb, M), np.int32),
            "active": np.zeros((Bb,), bool),
            "temp": np.ones((Bb,), np.float32),
            "topk": np.zeros((Bb,), np.int32),
            "topp": np.ones((Bb,), np.float32),
            "slot_idx": np.zeros((Bb,), np.int32),
            "has_image": np.zeros((Bb,), np.float32),
            "cross_len": np.full((Bb,), max(self.cross_seq_len, 1),
                                 np.int32),
            # mixed-phase row metadata (SHAI_FUSED_STEP / obs): each row's
            # decode start (its prompt boundary in cache tokens — stable
            # per decode segment, so the tables-only refresh path never
            # leaves it stale) and phase (0 = decode; mid-prefill slots
            # never enter the running view — the fused dispatch composes
            # its chunk rows itself, phase 1 lives only in that window)
            "starts": np.zeros((Bb,), np.int32),
            "phase": np.zeros((Bb,), np.int8),
        }
        for i, s in enumerate(running):
            a["tables"][i] = self.cache.seq(s.req.req_id).table(M)
            a["active"][i] = True
            a["temp"][i] = s.req.params.temperature
            a["topk"][i] = s.req.params.top_k
            a["topp"][i] = s.req.params.top_p
            a["slot_idx"][i] = s.slot
            a["has_image"][i] = self._has_image[s.slot]
            a["cross_len"][i] = self._cross_len[s.slot]
            a["starts"][i] = s.req.prefix_len + len(s.req.prompt_ids)
        return a

    def _spec_step(self) -> bool:
        """One speculative decode step: draft per running slot, verify all
        drafts (+ the bonus position) in one multi-token executable, commit
        the longest model-agreed prefix, roll back the rest.

        Returns False — without touching the cache — when no slot drafted
        anything; the caller falls through to the vanilla single-token
        decode executable (one dispatch, no k+1 overcompute).
        """
        k = self.ecfg.num_speculative_tokens
        running = self._running_slots()
        if not running:
            return False
        drafts: Dict[int, List[int]] = {}
        for s in running:
            p = s.req.params
            # a draft must leave room for its own commit: stay inside the
            # request's token budget AND the model-length budget (the cache
            # reservation below must never trip the max_model_len guard)
            cap = min(k, p.max_new_tokens - len(s.generated) - 1,
                      self.ecfg.max_model_len
                      - self.cache.seq(s.req.req_id).n_tokens - 1)
            if cap <= 0:
                drafts[s.slot] = []
                continue
            ctx = s.req.prompt_ids + s.generated + [s.pending_token]
            drafts[s.slot] = self._drafter.draft(ctx)[:cap]
        if not any(drafts.values()):
            self.spec.fallback_steps += 1
            return False
        # reserve 1 + draft_len tokens per slot (pending + drafts) before
        # the verify call; pool pressure preempts exactly as vanilla decode
        self._grow_running(lambda s: 1 + len(drafts.get(s.slot, ())))
        running = self._running_slots()
        if not running:
            return True  # everything preempted away; step is done
        n_exec = self.n_executables
        Bb, verify = self._verify_for(self._max_ctx_blocks(running),
                                      len(running))
        self._note_dispatch_pad(running, Bb, rows_per_seq=k + 1)

        # verify shares the device-resident batch view with decode: same
        # composition, same persistent tables/knob arrays — only the
        # per-step token/position data is marshaled fresh
        a = self._res.refresh(self, running, Bb)
        tokens = np.zeros((Bb, k + 1), np.int32)
        pos0 = np.zeros((Bb,), np.int32)
        n_drafted = [len(drafts.get(s.slot, ())) for s in running]
        for i, s in enumerate(running):
            d = drafts.get(s.slot, [])
            tokens[i, 0] = s.pending_token
            tokens[i, 1:1 + len(d)] = d
            pos0[i] = self.cache.seq(s.req.req_id).n_tokens - (1 + len(d))

        # same device stream slot as the vanilla decode this step replaces
        rng = jax.random.fold_in(self._rng, self._step_count * 2)
        args = [self.params, self.cache.kv, jnp.asarray(tokens),
                jnp.asarray(pos0), a["tables"], a["active"], rng,
                a["temp"], a["topk"], a["topp"]]
        if self._cross_kv is not None:
            args += [self._cross_kv, a["has_image"], a["slot_idx"],
                     a["cross_len"]]
        t_d = time.monotonic()
        with annotate("engine.verify"):
            (self.cache.kv, o, oex, accept_p, o_lp, d_lp, oex_lp,
             top_ids, top_lp) = verify(*args)
        if self._t_fetch and self.n_executables == n_exec \
                and self._last_decode_step == self._step_count - 1:
            self.obs.step_gap.observe(max(0.0, t_d - self._t_fetch))
        self._last_decode_step = self._step_count
        o = np.asarray(o)
        oex = np.asarray(oex)
        accept_p = np.asarray(accept_p)
        want_lp = any(s.req.params.logprobs for s in running)
        if want_lp:
            o_lp = np.asarray(o_lp)
            d_lp = np.asarray(d_lp)
            oex_lp = np.asarray(oex_lp)
            top_ids = np.asarray(top_ids)
            top_lp = np.asarray(top_lp)
        self._t_fetch = time.monotonic()

        from .speculative import accept_drafts

        self.spec.verify_steps += 1
        for i, s in enumerate(running):
            if self.slots[s.slot] is not s:
                continue  # defensive: slot changed mid-step
            d = drafts.get(s.slot, [])
            nd = n_drafted[i]
            p = s.req.params
            j, next_tok = accept_drafts(
                d, o[i], oex[i], accept_p[i], p.temperature,
                self._spec_rng.random(nd) if p.temperature > 0.0
                else np.zeros(nd))
            # give back what verification rejected: the cache reservation
            # shrinks to exactly the committed tokens (atomic commit)
            self.cache.shrink(s.req.req_id, nd - j)
            committed = [s.pending_token] + [int(t) for t in d[:j]]
            n_processed = 0  # tokens the commit walk actually reaches: an
            # EOS/length finish mid-run must not inflate tokens_per_verify
            finished = False
            for m, c in enumerate(committed):
                n_processed += 1
                self._tokens_this_step += 1  # perf-sentinel feed
                s.generated.append(c)
                hit_eos = c == p.eos_id
                if hit_eos:
                    s.generated.pop()  # exclude EOS from the emitted text
                    if p.logprobs and s.lps:
                        s.lps.pop()    # its lp entry goes with it
                elif s.req.on_token is not None:
                    s.req.on_token(c)  # stream the committed token
                full = len(s.generated) >= p.max_new_tokens
                out_of_len = pos0[i] + m + 1 >= self.ecfg.max_model_len
                if hit_eos or full or out_of_len:
                    self._record_tpot(s)
                    self._finish(Finished(
                        s.req.req_id, s.req.already_generated + s.generated,
                        s.req.orig_n_prompt, "eos" if hit_eos else "length",
                        logprobs=((s.req.already_lp + s.lps)
                                  if p.logprobs else None),
                        timing=self._timing_of(s.req, s.t_first)))
                    self._release_slot(s)
                    finished = True
                    break
                if p.logprobs:
                    # entry for this token's successor, exactly when vanilla
                    # would record it (at sample time): the next accepted
                    # draft, or the verify sample that ends the chain
                    if m < j:
                        s.lps.append(self._lp_entry(
                            p.logprobs, committed[m + 1], d_lp[i, m],
                            top_ids[i, m], top_lp[i, m]))
                    else:
                        tok_lp = (o_lp[i, j] if (j == nd
                                                 or p.temperature <= 0.0)
                                  else oex_lp[i, j])
                        s.lps.append(self._lp_entry(
                            p.logprobs, next_tok, tok_lp,
                            top_ids[i, j], top_lp[i, j]))
            self.spec.record_verify(nd, j, n_processed)
            if not finished:
                s.pending_token = next_tok
        return True

    def _decode_step(self) -> None:
        if self._drafter is not None and self._spec_step():
            self._step_kind = "spec"
            return
        self._step_kind = "decode"
        # grow each running seq by one slot for the pending token; preempt
        # on pool exhaustion (never preempt down to zero running sequences)
        self._grow_running(lambda s: 1)
        running = self._running_slots()
        if not running:
            self._flush_chunk()  # chunk-only step: no decode to ride
            return
        n_exec = self.n_executables
        Bb, decode = self._decode_for(self._max_ctx_blocks(running),
                                      len(running))
        self._note_dispatch_pad(running, Bb)

        a = self._marshal_running(running, Bb)
        tokens = np.zeros((Bb,), np.int32)
        pos = np.zeros((Bb,), np.int32)
        for i, s in enumerate(running):
            tokens[i] = s.pending_token
            pos[i] = self.cache.seq(s.req.req_id).n_tokens - 1

        rng = jax.random.fold_in(self._rng, self._step_count * 2)
        args = [self.params, self.cache.kv, jnp.asarray(tokens),
                jnp.asarray(pos), jnp.asarray(a["tables"]),
                jnp.asarray(a["active"]), rng, jnp.asarray(a["temp"]),
                jnp.asarray(a["topk"]), jnp.asarray(a["topp"])]
        if self._cross_kv is not None:
            args += [self._cross_kv, jnp.asarray(a["has_image"]),
                     jnp.asarray(a["slot_idx"]), jnp.asarray(a["cross_len"])]
        t_d = time.monotonic()
        with annotate("engine.decode"):
            self.cache.kv, nxt, top_ids_d, top_lp_d, tok_lp_d = decode(*args)
        if self._t_fetch and self.n_executables == n_exec \
                and self._last_decode_step == self._step_count - 1:
            # lock-step inter-step gap: the host work (marshal, bookkeeping)
            # the device idled behind between consecutive decode dispatches
            # (a first-use compile is warmup, not a dispatch gap — skipped)
            self.obs.step_gap.observe(max(0.0, t_d - self._t_fetch))
        self._last_decode_step = self._step_count
        nxt = np.asarray(nxt)
        if any(s.req.params.logprobs for s in running):
            top_ids_d = np.asarray(top_ids_d)
            top_lp_d = np.asarray(top_lp_d)
            tok_lp_d = np.asarray(tok_lp_d)
        else:
            top_ids_d = top_lp_d = tok_lp_d = None
        self._t_fetch = time.monotonic()

        self._commit_pending(running)
        self._apply_sampled(running, nxt, top_ids_d, top_lp_d, tok_lp_d)

    def _commit_pending(self, running) -> None:
        """Commit every running slot's pending token — the host half of a
        decode step: append/stream it, run the EOS/length/stop ladder, and
        finish+release what's done. Shared verbatim by the lock-step and
        async paths so the two disciplines cannot drift. Slots finished or
        cancelled since the snapshot are skipped (identity check)."""
        for s in running:
            if self.slots[s.slot] is not s:
                continue  # defensive: slot changed mid-step
            s.generated.append(s.pending_token)
            self._tokens_this_step += 1  # perf-sentinel throughput feed
            p = s.req.params
            hit_eos = s.pending_token == p.eos_id
            if hit_eos:
                s.generated.pop()  # exclude EOS from the emitted text
                if p.logprobs and s.lps:
                    s.lps.pop()    # its lp entry goes with it
            elif s.req.on_token is not None:
                s.req.on_token(s.pending_token)  # stream the committed token
            full = len(s.generated) >= p.max_new_tokens
            total = self.cache.seq(s.req.req_id).n_tokens
            out_of_len = total >= self.ecfg.max_model_len
            if hit_eos or full or out_of_len:
                self._record_tpot(s)
                self._finish(Finished(
                    s.req.req_id, s.req.already_generated + s.generated,
                    s.req.orig_n_prompt, "eos" if hit_eos else "length",
                    logprobs=((s.req.already_lp + s.lps)
                              if p.logprobs else None),
                    timing=self._timing_of(s.req, s.t_first)))
                if self._prefill_role:
                    # prefill-role handoff: bank the finished prompt's KV
                    # in the host tier BEFORE release so a peer decode pod
                    # can pull it the moment the serving layer returns the
                    # handoff (kvnet; failures degrade to peer recompute)
                    self.cache.demote_prompt_run(s.req.req_id,
                                                 s.req.prompt_ids)
                self._release_slot(s)

    def _apply_sampled(self, running, nxt, top_ids, top_lp, tok_lp) -> None:
        """Mirror a decode dispatch's sampled tokens into the surviving
        slots' ``pending_token`` (+ logprob entries). In the async path this
        runs one step late (the host mirror lags the device by one step);
        a slot finished/cancelled in between keeps its token discarded."""
        for i, s in enumerate(running):
            if self.slots[s.slot] is not s:
                continue  # finished/cancelled: the sampled token is dropped
            s.pending_token = int(nxt[i])
            p = s.req.params
            if p.logprobs:
                s.lps.append(self._lp_entry(
                    p.logprobs, nxt[i], tok_lp[i], top_ids[i], top_lp[i]))
