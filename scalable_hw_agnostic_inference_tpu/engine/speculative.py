"""Speculative decoding: prompt-lookup drafting + acceptance bookkeeping.

The engine commits exactly one token per decode dispatch, so decode
throughput is pinned to one paged-attention call per token. Speculative
decoding breaks that: a *drafter* proposes up to ``num_speculative_tokens``
continuations, one pre-compiled verify executable (``runner.make_verify``)
scores all of them plus the bonus position in a single paged-attention
call, and the engine commits the longest prefix the model itself agrees
with. Worst case costs one verify step per committed token (same dispatch
count as vanilla decode); best case commits ``k + 1`` tokens per step.

The drafter here is vLLM's ``speculative_model: "[ngram]"`` — pure prompt
lookup (match the tail n-gram of prompt+generated against earlier context,
propose what followed last time), no draft model, no extra weights, runs on
the host. It shines on the workloads the reference stack actually serves:
summarization/extraction-style prompts where the output quotes the input,
and the self-repetition every greedy decode drifts into.

Async-decode interplay (``SHAI_ASYNC_DECODE``, engine.resident): drafting
reads each slot's ``pending_token``, so a speculative step is a pipeline
*event* — the engine flushes (retires) any in-flight lookahead dispatch
before ``_spec_step`` runs, and the verify dispatch shares the
device-resident batch view (tables/active/sampling knobs) with decode
instead of re-marshaling it host->device per step.

Acceptance is exact: at temperature 0 a draft survives iff it equals the
model's argmax at its position; at temperature > 0 the standard
delta-proposal rejection rule applies — accept draft ``d`` with probability
``p_target(d)`` (the n-gram proposal is a point mass, so ``q(d) = 1``), and
on rejection resample from the target distribution with ``d`` masked out
(``oex`` below, sampled in-graph). Either way every committed token is
distributed exactly as vanilla decode; drafts only ever change speed.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np


@dataclasses.dataclass
class SpecStats:
    """Cumulative speculative-decoding counters (one per engine).

    ``acceptance_rate`` is accepted/drafted — the knob the cost model keys
    on (perf.model.spec_decode_model); ``tokens_per_verify`` is the realized
    commit rate per verify dispatch (1.0 == vanilla decode pace).
    """

    drafted: int = 0        # draft tokens submitted to verification
    accepted: int = 0       # draft tokens that survived verification
    committed: int = 0      # tokens committed via verify steps (incl. bonus)
    verify_steps: int = 0   # multi-token verify dispatches
    fallback_steps: int = 0  # steps that fell back to vanilla decode

    def record_verify(self, n_drafted: int, n_accepted: int,
                      n_processed: int) -> None:
        """One sequence's verification outcome: drafted/accepted count the
        VERIFICATION result (drafter-quality signal); ``n_processed`` the
        tokens the commit walk actually reached (an EOS/length finish
        mid-run must not inflate tokens_per_verify)."""
        self.drafted += n_drafted
        self.accepted += n_accepted
        self.committed += n_processed

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def tokens_per_verify(self) -> float:
        return self.committed / self.verify_steps if self.verify_steps else 0.0

    def as_dict(self) -> dict:
        return {
            "spec_drafted": self.drafted,
            "spec_accepted": self.accepted,
            "spec_committed": self.committed,
            "spec_verify_steps": self.verify_steps,
            "spec_fallback_steps": self.fallback_steps,
            "spec_acceptance_rate": round(self.acceptance_rate, 4),
            "spec_tokens_per_verify": round(self.tokens_per_verify, 4),
        }


class PromptLookupDrafter:
    """Model-free n-gram drafter (vLLM's ``[ngram]`` speculative model).

    ``draft(context)`` matches the last ``n`` tokens of the context
    (``n`` from ``lookup_max`` down to ``lookup_min``) against every earlier
    position, most recent occurrence first, and proposes the up-to-``k``
    tokens that followed that occurrence. No weights, no device traffic —
    the proposal is a pure host-side list scan, cheap next to a decode
    dispatch.
    """

    def __init__(self, k: int, lookup_max: int = 4, lookup_min: int = 1):
        if k < 1:
            raise ValueError("num_speculative_tokens must be >= 1")
        if not 1 <= lookup_min <= lookup_max:
            raise ValueError(
                f"need 1 <= ngram_prompt_lookup_min ({lookup_min}) <= "
                f"ngram_prompt_lookup_max ({lookup_max})")
        self.k = k
        self.lookup_max = lookup_max
        self.lookup_min = lookup_min

    def draft(self, context: Sequence[int]) -> List[int]:
        """Propose up to ``k`` continuation tokens for ``context``; ``[]``
        when the history is too short or no earlier n-gram matches.

        The scan is numpy-vectorized (sliding-window compare, C speed):
        this runs per running slot per decode step, and its worst case —
        no match anywhere, vanilla fallback — is exactly the case that
        must stay cheap next to a decode dispatch.
        """
        ctx = list(context)
        L = len(ctx)
        if L < self.lookup_min + 1:
            return []
        arr = np.asarray(ctx, dtype=np.int64)
        # longest n-grams first: a longer match is a stronger predictor
        for n in range(min(self.lookup_max, L - 1), self.lookup_min - 1, -1):
            tail = arr[L - n:]
            # candidate starts 0..L-n-1: the match must END strictly before
            # the final position so the continuation is non-empty
            windows = np.lib.stride_tricks.sliding_window_view(
                arr[:L - 1], n)
            hits = np.flatnonzero((windows == tail).all(axis=1))
            if hits.size:
                start = int(hits[-1])  # most recent earlier occurrence
                return ctx[start + n:start + n + self.k]
        return []


def accept_drafts(draft: Sequence[int], o, oex, accept_p,
                  temperature: float, uniforms) -> tuple:
    """Host-side acceptance walk for ONE sequence.

    ``o[i]`` is the model's sample at draft position ``i`` (full target
    distribution), ``oex[i]`` a sample with ``draft[i]`` masked out,
    ``accept_p[i]`` the target probability of ``draft[i]`` under the actual
    sampling distribution. ``uniforms`` supplies the rejection draws
    (ignored at temperature 0, where acceptance is exact argmax match).

    Returns ``(n_accepted, next_token)`` — the committed tokens are
    ``pending + draft[:n_accepted]`` and ``next_token`` becomes the new
    pending token (the bonus sample when everything was accepted).
    """
    nd = len(draft)
    for i in range(nd):
        if temperature <= 0.0:
            ok = int(draft[i]) == int(o[i])
        else:
            ok = float(uniforms[i]) < float(accept_p[i])
        if not ok:
            # rejection-resample: at temperature 0 the argmax IS the
            # corrected sample; otherwise sample from p with draft[i] out
            nxt = int(o[i]) if temperature <= 0.0 else int(oex[i])
            return i, nxt
    return nd, int(o[nd])
