"""Engine request/response dataclasses (split from engine.py, r4 weak #5)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from .config import EngineConfig


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    max_new_tokens: int = 128
    eos_id: int = -1            # -1: never stop on a token
    # report per-token logprobs with this many top alternatives (0 = off,
    # capped at runner.K_LOGPROBS — the OpenAI `logprobs` field)
    logprobs: int = 0

    def clamp(self, ecfg: EngineConfig) -> "SamplingParams":
        from .runner import K_LOGPROBS

        # global_topk == 0 means "cap disabled": leave a user-set top_k alone
        if self.top_k and ecfg.global_topk:
            top_k = min(self.top_k, ecfg.global_topk)
        else:
            top_k = self.top_k or ecfg.global_topk
        return dataclasses.replace(
            self,
            max_new_tokens=min(self.max_new_tokens, ecfg.max_new_tokens),
            top_k=top_k,
            logprobs=min(max(int(self.logprobs), 0), K_LOGPROBS),
        )


@dataclasses.dataclass
class Request:
    req_id: int
    prompt_ids: List[int]
    params: SamplingParams
    # soft-prefix embeddings [P, dim] (vision tokens — multimodal requests,
    # reference ``vllm_model_api_m.py:42-66``); occupy the first P positions
    prefix: Optional[np.ndarray] = None
    # mllama cross-attention states [Lv, dim] (projected vision features);
    # attended by the gated cross layers, never part of the token sequence.
    # cross_len: valid rows (multi-tile images fill a tile-count-dependent
    # prefix of the static buffer; 0/None = all rows valid)
    cross_states: Optional[np.ndarray] = None
    cross_len: int = 0
    # tokens generated before a recompute-preemption (they re-enter the
    # cache as prompt suffix but remain part of the client-visible output)
    already_generated: List[int] = dataclasses.field(default_factory=list)
    orig_n_prompt: int = -1
    # streaming: called (engine-loop thread, must be cheap — a queue put)
    # exactly once per token that will appear in Finished.token_ids, in order
    on_token: Optional[Any] = None
    # absolute monotonic deadline (0 = none): the engine expires the
    # request at step granularity wherever it is — queued, mid-prefill, or
    # decoding — finishing it with stop reason "timeout" so its KV blocks
    # and slot free instead of decoding past a budget nobody is waiting on.
    # Survives preemption (the budget is the request's, not the segment's).
    deadline_at: float = 0.0
    # submission time (monotonic) for TTFT accounting; survives preemption
    t_submit: float = 0.0
    # first-admission time (monotonic): queue-wait accounting. Survives
    # preemption like t_submit — a resume is not a second queue wait.
    t_admit: float = 0.0
    # true first-token time (monotonic): the prefill/decode boundary in
    # Finished.timing. Survives preemption — a resume's re-prefill belongs
    # to the decode phase it interrupted, not to prefill (the slot-level
    # t_first, which resets per segment, keeps TPOT per-segment-accurate)
    t_first: float = 0.0
    # logprob entries for tokens emitted before a preemption (mirrors
    # already_generated)
    already_lp: List = dataclasses.field(default_factory=list)
    # multi-tenant QoS (resilience.qos): priority class (0=high, 1=normal,
    # 2=low — LOWER is more important) drives the weighted-fair dequeue
    # and lowest-priority-first preemption; tenant attributes the request
    # in per-tenant budgets/metrics. Both survive preemption — the
    # re-queued remainder is the same tenant's same-priority work.
    priority: int = 1
    tenant: str = ""
    # request reliability (resilience.idempotency): the request's
    # idempotency key as minted/forwarded by cova — attribution only at
    # this layer (the serving layer owns the dedup cache), but it rides
    # the Request so the migration manifest can carry it and a resumed
    # duplicate dedupes on the peer through the SAME key. Survives
    # preemption. "" = keyless (replay protection off for this request).
    idem_key: str = ""
    # KV fabric (kvnet.directory): holder URLs the router believes hold
    # this prompt's leading KV run — a pushed-down directory slice. A
    # HINT only: the peer-probe rung tries them under its wall budget
    # and recomputes on any miss; empty = resolve via the pod-local
    # directory (or skip the probe entirely — the cold-fleet fast path)
    kv_holders: List[str] = dataclasses.field(default_factory=list)
    # distributed tracing (obs.trace): the request's W3C traceparent,
    # captured on the serving lane at submit time. The engine loop thread
    # has NO request contextvars, so cross-pod work it initiates itself
    # (the fabric-probe pull rung) forwards THIS header to keep one
    # request one trace. "" = untraced (SHAI_TRACE=0 or no active trace).
    traceparent: str = ""
    # engine-side trace attribution: sub-phase instants/durations the span
    # tree can't see from outside (fabric probe, kv restore, recompute
    # fallback, per-request pipeline flushes, migration cut), merged into
    # Finished.timing by _timing_of and grafted as spans/attrs by the
    # serving layer (Trace.add_phase_spans). Engine-loop-thread-only.
    obs_extra: Dict[str, float] = dataclasses.field(default_factory=dict)
    # n>1 sampling fan-out (SHAI_KV_COW): siblings of one OpenAI request
    # share a parent id (-1 = not a fan-out member). The engine admits a
    # fully-queued group as ONE prefill with copy-on-write KV forks, and
    # the loop cancels/expires the group as a unit. Deliberately NOT
    # carried across preemption re-queues — a resumed sibling has its own
    # generated suffix and must re-admit independently.
    parent_rid: int = -1

    def __post_init__(self):
        if self.orig_n_prompt < 0:
            self.orig_n_prompt = len(self.prompt_ids)

    @property
    def prefix_len(self) -> int:
        return 0 if self.prefix is None else int(self.prefix.shape[0])


@dataclasses.dataclass
class Finished:
    req_id: int
    token_ids: List[int]        # generated tokens, EOS excluded
    n_prompt: int
    # "eos" | "length" | "rejected" | "cancelled" | "timeout" | "migrated"
    stop_reason: str
    # one entry per token_ids element when the request asked for logprobs:
    # {"token", "logprob", "top_ids", "top_logprobs"}
    logprobs: Optional[List[Dict[str, Any]]] = None
    # per-phase timeline (obs): monotonic stamps t_submit/t_admit/t_first/
    # t_done plus derived queue_s/prefill_s/decode_s/total_s — the serving
    # layer turns these into request-trace spans and bench.py aggregates
    # them into per-phase report fields
    timing: Optional[Dict[str, float]] = None
    # live migration (kvnet.migrate): stop_reason "migrated" carries the
    # sequence's resumable manifest — prompt+generated token ids, remaining
    # sampling budget, QoS identity, deadline remainder, and the chain
    # hashes of the KV run banked in the host tier. The serving layer ships
    # it to a peer and the request CONTINUES there; a "migrated" Finished
    # is a handoff, not a terminal outcome.
    migration: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class _Running:
    req: Request
    slot: int
    generated: List[int]
    pending_token: int          # sampled but not yet written to the cache
    # chunked prefill: prompt position of the next chunk, or None when the
    # prompt is fully encoded (mid-prefill slots don't join the decode batch)
    prefill_cursor: Optional[int] = None
    t_first: float = 0.0        # first-token time (TPOT accounting)
    # logprob entries in sample order (== append order); only populated
    # when the request asked for logprobs
    lps: List = dataclasses.field(default_factory=list)


