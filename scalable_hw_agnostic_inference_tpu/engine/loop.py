"""Engine loop thread: the bridge between concurrent HTTP and one engine.

vLLM's AsyncLLMEngine equivalent, sized down: one daemon thread owns the
engine (and through it the device); callers submit token-id prompts and wait
on a future. Concurrent requests naturally coalesce into the running batch —
this is where continuous batching actually pays off in serving (the
reference gets it inside ``vllm.LLM``; our serving lane is widened to
``max_num_seqs`` so requests reach the loop concurrently, see
``serve.app.ModelService.concurrency``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

from .engine import Finished, LLMEngine, SamplingParams

log = logging.getLogger(__name__)


class EngineLoop:
    def __init__(self, engine: LLMEngine, poll_s: float = 0.005):
        self.engine = engine
        # items: (prompt_ids, params, extras, future) — or the fan-out
        # group form (prompt_ids, [params]*K, extras, [future]*K), told
        # apart by the future slot holding a list
        self._submit_q: "queue.Queue[Tuple[List[int], SamplingParams, Optional[object], Future]]" = (
            queue.Queue()
        )
        self._futures: dict[int, Future] = {}
        self._futures_lock = threading.Lock()
        self._cancel_q: "queue.Queue[Future]" = queue.Queue()
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._draining = threading.Event()
        # live migration (kvnet.migrate): the drain thread arms
        # _migrate_evt; the LOOP thread performs the snapshot+finish for
        # every live request (the engine is single-owner — a snapshot off
        # the loop thread would race the step), then sets _migrate_done.
        self._migrate_evt = threading.Event()
        self._migrate_done = threading.Event()
        self._migrate_count = 0  # loop-thread write, read after _done
        self._thread = threading.Thread(target=self._run, name="engine-loop",
                                        daemon=True)

    def start(self) -> "EngineLoop":
        self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        """True while the loop thread runs and accepts work.

        A crashed ``engine.step()`` sets ``_stop`` (the loop refuses new
        submissions) — the serving layer surfaces that into ``/readiness`` so
        the LB stops routing to a pod that can only 500 (VERDICT r2 weak #6;
        the reference's equivalent failure kills the process and the probe
        catches it).
        """
        return self._thread.is_alive() and not self._stop.is_set()

    def stop(self, timeout: float = 5.0) -> None:
        """Signal the loop to exit; its exit path fails outstanding futures."""
        self._stop.set()
        self._thread.join(timeout)

    def drain(self, budget_s: float = 30.0) -> bool:
        """Graceful shutdown: refuse new submissions, let in-flight requests
        run to completion for up to ``budget_s`` seconds, then stop the
        loop. Returns True when everything finished inside the budget;
        False means the budget expired with work still in flight (those
        futures fail with "engine loop is stopped" on the way out)."""
        self._draining.set()
        deadline = time.monotonic() + max(0.0, budget_s)
        drained = False
        while True:
            with self._futures_lock:
                outstanding = bool(self._futures)
            if (not outstanding and self._submit_q.empty()
                    and not self.engine.has_work):
                drained = True
                break
            if time.monotonic() >= deadline:
                log.warning("drain budget (%.1fs) expired with work in "
                            "flight — stopping anyway", budget_s)
                break
            time.sleep(self._poll_s)
        self.stop()
        return drained

    def submit(self, prompt_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               prefix=None, cross_states=None, cross_len: int = 0,
               on_token=None, deadline_at: float = 0.0,
               priority: int = 1, tenant: str = "",
               already_generated: Optional[Sequence[int]] = None,
               already_lp: Optional[list] = None,
               orig_n_prompt: int = -1,
               kv_holders: Optional[Sequence[str]] = None,
               traceparent: str = "", idem_key: str = "") -> Future:
        """Enqueue a request; the future resolves to a :class:`Finished`.

        ``prefix``: optional soft-prefix embeddings [P, dim] (vision tokens,
        LLaVA-style). ``cross_states``: optional mllama cross-attention
        states [Lv, dim] (gated cross layers attend them). ``on_token``:
        streaming callback — called from the loop thread, once per output
        token, in order; must be cheap (a queue put). ``deadline_at``:
        absolute monotonic deadline (0 = none) — the engine expires the
        request with stop reason ``"timeout"`` once passed. ``priority``/
        ``tenant``: QoS class and tenant attribution (``resilience.qos``)
        for the weighted-fair dequeue and per-tenant accounting.
        """
        if self._stop.is_set():
            raise RuntimeError("engine loop is stopped")
        if self._draining.is_set():
            # the admission gate sheds with a 503 before reaching here;
            # this guards direct submitters during the drain window
            raise RuntimeError("engine loop is draining")
        fut: Future = Future()
        self._submit_q.put(
            (list(prompt_ids), params or SamplingParams(),
             (prefix, cross_states, cross_len, on_token, deadline_at,
              priority, tenant, already_generated, already_lp,
              orig_n_prompt, kv_holders, traceparent, idem_key), fut))
        # close the put-after-drain window: if the loop died between our
        # _stop check and the put, nobody will ever drain this item
        if self._stop.is_set():
            self._fail_all(RuntimeError("engine loop is stopped"))
        return fut

    def submit_group(self, prompt_ids: Sequence[int],
                     params_list: Sequence[SamplingParams], *,
                     on_tokens: Optional[Sequence] = None,
                     deadline_at: float = 0.0, priority: int = 1,
                     tenant: str = "") -> List[Future]:
        """n>1 sampling fan-out: ONE tokenized prompt, K sampling-param
        sets, K futures. The whole group rides one queue item so the loop
        admits the siblings back-to-back — fully queued together, which
        is what lets the engine admit them as a single prefill with
        copy-on-write KV forks (``SHAI_KV_COW``) — and tags them with one
        parent id so cancel/deadline/migration treat the fan-out as a
        unit (cancelling any member aborts the whole group)."""
        if self._stop.is_set():
            raise RuntimeError("engine loop is stopped")
        if self._draining.is_set():
            raise RuntimeError("engine loop is draining")
        futs: List[Future] = [Future() for _ in params_list]
        self._submit_q.put(
            (list(prompt_ids), list(params_list),
             (list(on_tokens) if on_tokens else [None] * len(futs),
              deadline_at, priority, tenant), futs))
        if self._stop.is_set():
            self._fail_all(RuntimeError("engine loop is stopped"))
        return futs

    def migrate_all(self, timeout: float = 10.0) -> int:
        """Drain-time live migration: refuse new submissions, then have
        the LOOP thread finish every queued + running request with stop
        reason ``"migrated"`` (manifest attached — the serving-layer
        waiters ship it to a peer). Blocks until the loop thread has
        processed the sweep or ``timeout`` expires; callable from the
        drain thread. Returns how many requests migrated. Requests the
        engine declines to migrate (multimodal state) keep running — the
        ordinary drain wait covers them."""
        self._draining.set()
        if not self.alive:
            return 0
        self._migrate_done.clear()
        self._migrate_evt.set()
        if not self._migrate_done.wait(max(0.0, timeout)):
            return 0
        return self._migrate_count

    def _do_migrate_all(self) -> None:
        """Loop-thread half of :meth:`migrate_all`: snapshot-and-finish
        every live request, resolving its future with the migrated
        Finished. Runs after the submit queue drained and with
        ``_draining`` set, so no request can slip in behind the sweep."""
        n = 0
        with self._futures_lock:
            rids = list(self._futures)
        for rid in rids:
            try:
                fin = self.engine.migrate_out(rid)
            except Exception:
                log.exception("migrate_out(%d) failed — request keeps "
                              "running under the ordinary drain", rid)
                continue
            if fin is None:
                continue  # unknown/unmigratable: the drain wait covers it
            if fin.stop_reason == "migrated":
                n += 1
            with self._futures_lock:
                fut = self._futures.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(fin)
        self._migrate_count = n

    def cancel(self, fut: Future) -> None:
        """Request cancellation of a submitted request (async: the loop
        thread aborts it between steps and resolves the future with a
        partial ``"cancelled"`` Finished). Safe to call when the request
        already finished — it's a no-op then."""
        self._cancel_q.put(fut)

    # -- loop --------------------------------------------------------------

    def _drain_submissions(self, block: bool) -> None:
        try:
            item = self._submit_q.get(timeout=self._poll_s if block else None) \
                if block else self._submit_q.get_nowait()
        except queue.Empty:
            return
        while True:
            ids, params, extras, fut = item
            if isinstance(fut, list):  # submit_group fan-out item
                self._admit_group(ids, params, extras, fut)
            else:
                (prefix, cross_states, cross_len, on_token, deadline_at,
                 priority, tenant, already_generated, already_lp,
                 orig_n_prompt, kv_holders, traceparent, idem_key) = extras
                try:
                    rid = self.engine.add_request(
                        ids, params, prefix=prefix,
                        cross_states=cross_states, cross_len=cross_len,
                        on_token=on_token, deadline_at=deadline_at,
                        priority=priority, tenant=tenant,
                        already_generated=already_generated,
                        already_lp=already_lp, orig_n_prompt=orig_n_prompt,
                        kv_holders=kv_holders, traceparent=traceparent,
                        idem_key=idem_key)
                    with self._futures_lock:
                        self._futures[rid] = fut
                except Exception as e:  # bad request (e.g. empty prompt)
                    fut.set_exception(e)
            try:
                item = self._submit_q.get_nowait()
            except queue.Empty:
                return

    def _admit_group(self, ids, params_list, extras, futs) -> None:
        """Admit one fan-out group: K sibling requests sharing a prompt
        and a parent id (first admitted member leads). A member whose
        add_request raises fails only its own future — the engine-side
        group-admission guards simply see a smaller group."""
        on_tokens, deadline_at, priority, tenant = extras
        parent = -2  # sentinel: first admitted sibling becomes the parent
        for on_token, params, fut in zip(on_tokens, params_list, futs):
            try:
                rid = self.engine.add_request(
                    ids, params, on_token=on_token,
                    deadline_at=deadline_at, priority=priority,
                    tenant=tenant, parent_rid=parent)
                if parent == -2:
                    parent = rid
                with self._futures_lock:
                    self._futures[rid] = fut
            except Exception as e:
                fut.set_exception(e)

    def _fail_all(self, err: Exception) -> None:
        """Fail every queued and in-flight future (loop death / stop).
        Futures resolve OUTSIDE the lock — set_exception wakes waiters
        and runs done-callbacks inline, and the futures table lock must
        never be held across foreign code (same discipline as the happy
        path in ``_run``; shai-race lock-order contract)."""
        pending: List[Future] = []
        with self._futures_lock:
            while True:
                try:
                    *_, fut = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                pending.append(fut)
            pending.extend(self._futures.values())
            self._futures.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(err)

    def _drain_cancels(self) -> None:
        while True:
            try:
                fut = self._cancel_q.get_nowait()
            except queue.Empty:
                return
            with self._futures_lock:
                rid = next((r for r, f in self._futures.items() if f is fut),
                           None)
            if rid is None:
                continue  # already finished (or never admitted)
            # fan-out groups cancel as a UNIT: aborting any sibling aborts
            # them all (one OpenAI n>1 request is one deliverable — a
            # partial group decodes for nobody). fanout_siblings returns
            # [rid] for ordinary requests, so this is the plain path too.
            for sib in self.engine.fanout_siblings(rid):
                fin = self.engine.cancel(sib)
                if fin is None:
                    continue
                with self._futures_lock:
                    sfut = self._futures.pop(sib, None)
                if sfut is not None and not sfut.done():
                    sfut.set_result(fin)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                # block for work only when idle; never between engine steps
                self._drain_submissions(block=not self.engine.has_work)
                self._drain_cancels()
                if self._migrate_evt.is_set():
                    self._migrate_evt.clear()
                    try:
                        self._do_migrate_all()
                    finally:
                        self._migrate_done.set()
                if not self.engine.has_work:
                    # async decode: going idle can leave the final lookahead
                    # step in flight (every slot finished at its commit) —
                    # retire it here so host mirrors don't sit one step
                    # stale across the idle gap and its buffers free
                    self.engine.finish_pending()
                    continue
                try:
                    for fin in self.engine.step():
                        with self._futures_lock:
                            fut = self._futures.pop(fin.req_id, None)
                        if fut is not None:
                            fut.set_result(fin)
                except Exception:
                    log.exception("engine step failed")
                    self._stop.set()  # dead loop must refuse new submissions
                    raise
        finally:
            # sole cleanup point: runs on clean stop AND on crash, from the
            # loop thread itself, so callers never race live future updates
            self._fail_all(RuntimeError("engine loop is stopped"))
