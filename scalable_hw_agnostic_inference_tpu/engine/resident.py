"""Device-resident decode batch state + the in-flight lookahead record.

The lock-step decode loop re-marshals the full batch view host->device on
EVERY step — seven ``jnp.asarray`` uploads for arrays that change at most
when the batch composition changes — then blocks on ``np.asarray(nxt)``
before doing its host bookkeeping, stacking a fixed serial host gap onto
every HBM-bound decode step. The async pipeline (``SHAI_ASYNC_DECODE``)
removes both halves:

* :class:`ResidentBatch` keeps the composition-dependent arrays
  (``tables/active/temp/topk/topp`` plus the mllama slot tail) as
  persistent DEVICE arrays, keyed by a composition signature. They are
  re-uploaded only when the signature changes (join/finish/preempt) —
  block-table growth alone refreshes just the ``tables`` upload. The
  speculative verify path shares this cache: same composition, same
  device arrays, whichever executable dispatches next.

  The mirror is COLUMN-AGNOSTIC: whatever dict ``engine.
  _marshal_running`` returns is uploaded wholesale, so per-row metadata
  columns ride along without touching the refresh mechanics. The fused
  mixed-phase step (``SHAI_FUSED_STEP``) adds two: ``starts`` (each
  row's decode start — its prompt boundary in cache tokens, constant
  per decode segment by CONTRACT, which is what keeps the tables-only
  refresh path truthful) and ``phase`` (int8, 0 = decode for every
  resident row; the fused dispatch composes its chunk-window rows
  itself — a nonzero phase never appears in resident state).

* :class:`InflightStep` records one dispatched-but-not-retired decode
  step: the device-side sampled tokens (which feed straight back as the
  next dispatch's ``tokens`` input — the host never sees them until one
  step later), the donated next-positions array, and the logprob outputs.
  Retiring the record is the ONLY place the host blocks on the device.

Layering: pure data + marshaling helpers; the scheduling policy (when to
flush, when to reuse) lives in ``engine.engine``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def composition_sig(running, Bb: int) -> Tuple:
    """Identity of the compacted batch view: which request sits in which
    batch row (and slot), at which executable batch bucket. Sampling knobs
    and the cross-attention tail are per-request constants, so the
    ``req_id`` entries cover them; anything this tuple does not capture —
    block-table growth/reassignment — is tracked separately (``blocks``)."""
    return (tuple((s.req.req_id, s.slot) for s in running), Bb)


@dataclasses.dataclass
class InflightStep:
    """One dispatched decode step awaiting retirement (host readback)."""

    sig: Tuple
    running: List[Any]                # _Running snapshot, batch-row order
    nxt: Any                          # device [Bb] sampled tokens (feedback)
    pos_next: Optional[Any]           # device [Bb] pos+1; None once donated
    top_ids: Any
    top_lp: Any
    tok_lp: Any
    want_lp: bool
    t_dispatch: float                 # monotonic enqueue stamp (gap metric)

    def device_bytes(self) -> int:
        """Bytes the un-retired step's outputs pin on device (HBM ledger)."""
        # shai-lint: allow(host-sync) .nbytes is host shape metadata
        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in (self.nxt, self.pos_next, self.top_ids,
                             self.top_lp, self.tok_lp))


class ResidentBatch:
    """Composition-keyed device mirror of the decode batch arrays."""

    def __init__(self) -> None:
        self.sig: Optional[Tuple] = None
        self.arrays: Dict[str, Any] = {}
        self.blocks: Tuple[Tuple[int, ...], ...] = ()

    def invalidate(self) -> None:
        self.sig = None
        self.arrays = {}
        self.blocks = ()

    def device_bytes(self) -> int:
        """Bytes the resident mirror holds on device (HBM ledger feed)."""
        # shai-lint: allow(host-sync) .nbytes is shape metadata (host int)
        return sum(int(getattr(a, "nbytes", 0)) for a in self.arrays.values())

    def refresh(self, engine, running, Bb: int) -> Dict[str, Any]:
        """Device arrays for ``running`` compacted into ``Bb`` rows.

        Composition unchanged: reuse every resident array, re-uploading
        only ``tables`` when some row's block LIST changed since the last
        marshal. Staleness is keyed on the block IDENTITIES, not counts:
        the allocator's free list is LIFO, so a shrink-then-regrow cycle
        (speculative rollback) can hand two slots each other's freed
        blocks with every per-row count unchanged — a count key would
        reuse tables that now point rows at the wrong physical blocks.
        Composition changed: one full host marshal (the engine's
        lock-step ``_marshal_running``) uploaded wholesale.
        """
        sig = composition_sig(running, Bb)
        blocks = tuple(tuple(engine.cache.seq(s.req.req_id).blocks)
                       for s in running)
        if sig == self.sig:
            if blocks != self.blocks:
                M = engine.ecfg.blocks_per_seq
                tables = np.zeros((Bb, M), np.int32)
                for i, s in enumerate(running):
                    tables[i] = engine.cache.seq(s.req.req_id).table(M)
                self.arrays["tables"] = jnp.asarray(tables)
                self.blocks = blocks
            return self.arrays
        host = engine._marshal_running(running, Bb)
        self.arrays = {k: jnp.asarray(v) for k, v in host.items()}
        self.sig = sig
        self.blocks = blocks
        return self.arrays
