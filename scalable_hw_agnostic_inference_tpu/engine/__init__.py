"""TPU-native continuous-batching LLM engine.

The reference consumes this capability through the vLLM neuron fork
(``LLM(**vllm_config.yaml)``, reference ``app/vllm_model_api.py:33-34``;
bucketing/continuous-batching knobs
``cova/mllama-32-11b-vllm-trn1-config.yaml:10-22``). Here the engine is
first-party: paged KV cache with host-side block allocation, bucketed
prefill, one jitted decode step for the whole running batch, on-device
sampling, and a continuous-batching scheduler — all static-shaped for XLA.
"""

from .cache import BlockAllocator, PagedKVCache  # noqa: F401
from .config import EngineConfig  # noqa: F401
from .speculative import PromptLookupDrafter, SpecStats  # noqa: F401
