"""Paged KV cache: device block pool + host-side block allocator.

The reference gets paged KV from vLLM's neuron fork (``block_size: 4096``,
reference ``cova/mllama-32-11b-vllm-trn1-config.yaml:16``). TPU-natively the
pool is one device array per layer ``[num_blocks, block_size, n_kv, head_dim]``
— block tables are *data* (int32 arrays), so one compiled executable serves
any allocation pattern; only bucket shapes trigger compiles.

Allocation is host-side and O(1) per block (free list). The device never
sees fragmentation: gathers go through block tables.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class BlockAllocator:
    """Free-list allocator over ``total_blocks`` physical blocks.

    Block 0 is reserved as the null block (block tables are padded with 0;
    its contents are garbage but always masked out by sequence lengths).
    """

    def __init__(self, total_blocks: int):
        if total_blocks < 2:
            raise ValueError("need at least 2 blocks (0 is reserved)")
        self.total = total_blocks
        self._free: List[int] = list(range(total_blocks - 1, 0, -1))

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"wanted {n} blocks, {len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == 0:
                raise ValueError("block 0 is reserved")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)


@dataclasses.dataclass
class SeqAllocation:
    """Host bookkeeping for one running sequence."""

    seq_id: int
    blocks: List[int]
    n_tokens: int = 0

    def table(self, blocks_per_seq: int) -> np.ndarray:
        t = np.zeros((blocks_per_seq,), np.int32)
        t[: len(self.blocks)] = self.blocks
        return t


class PagedKVCache:
    """Device block pool + per-sequence block accounting.

    ``kv`` is a pytree: per layer ``{"k": [N, Bs, Hkv, Dh], "v": ...}``.
    The jitted model paths update it functionally (donated) via
    :func:`write_prefill` / :func:`write_decode` in ``engine.runner``.
    """

    def __init__(self, n_layers: int, n_kv_heads: int, head_dim: int,
                 total_blocks: int, block_size: int, blocks_per_seq: int,
                 dtype=jnp.bfloat16, sharding=None):
        self.n_layers = n_layers
        self.block_size = block_size
        self.blocks_per_seq = blocks_per_seq
        self.allocator = BlockAllocator(total_blocks)
        shape = (total_blocks, block_size, n_kv_heads, head_dim)

        def zeros(name: str) -> jax.Array:
            z = jnp.zeros(shape, dtype)
            if sharding is not None:
                # tensor-parallel pool: split on the kv-head axis so each tp
                # rank owns its heads' blocks (sharding: {"k": NS, "v": NS})
                z = jax.device_put(z, sharding[name])
            return z

        self.kv = [{"k": zeros("k"), "v": zeros("v")} for _ in range(n_layers)]
        self._seqs: Dict[int, SeqAllocation] = {}

    # -- host-side sequence lifecycle --------------------------------------

    def can_admit(self, n_tokens: int) -> bool:
        return self._blocks_needed(n_tokens) <= self.allocator.n_free

    def admit(self, seq_id: int, n_tokens: int) -> SeqAllocation:
        """Allocate blocks to cover ``n_tokens`` prompt tokens."""
        if seq_id in self._seqs:
            raise ValueError(f"seq {seq_id} already admitted")
        alloc = SeqAllocation(seq_id, self.allocator.alloc(
            self._blocks_needed(n_tokens)), n_tokens)
        self._seqs[seq_id] = alloc
        return alloc

    def extend(self, seq_id: int, n_new: int = 1) -> SeqAllocation:
        """Grow a sequence by ``n_new`` tokens, allocating blocks as needed."""
        alloc = self._seqs[seq_id]
        need = self._blocks_needed(alloc.n_tokens + n_new) - len(alloc.blocks)
        if need > 0:
            if len(alloc.blocks) + need > self.blocks_per_seq:
                raise MemoryError(f"seq {seq_id} exceeds max_model_len")
            alloc.blocks.extend(self.allocator.alloc(need))
        alloc.n_tokens += n_new
        return alloc

    def release(self, seq_id: int) -> None:
        alloc = self._seqs.pop(seq_id)
        self.allocator.free(alloc.blocks)

    def seq(self, seq_id: int) -> SeqAllocation:
        return self._seqs[seq_id]

    @property
    def active(self) -> List[int]:
        return sorted(self._seqs)

    def _blocks_needed(self, n_tokens: int) -> int:
        return max(1, -(-n_tokens // self.block_size))
