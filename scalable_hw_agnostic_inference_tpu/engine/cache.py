"""Paged KV cache: device block pool + host-side block allocator.

The reference gets paged KV from vLLM's neuron fork (``block_size: 4096``,
reference ``cova/mllama-32-11b-vllm-trn1-config.yaml:16``). TPU-natively the
pool is one device array per layer ``[num_blocks, block_size, n_kv, head_dim]``
— block tables are *data* (int32 arrays), so one compiled executable serves
any allocation pattern; only bucket shapes trigger compiles.

Allocation is host-side and O(1) per block (free list). The device never
sees fragmentation: gathers go through block tables.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

#: the CLOSED set of pad sizes the tier movers compile for: demotion
#: gathers and restore scatters pad their index arrays to one of these
#: (padding rows target reserved block 0), so attach-time priming covers
#: every shape the post-ready path can dispatch
_PAD_SIZES = (1, 2, 4, 8)
_PAD_MAX = _PAD_SIZES[-1]


def _pad_size(n: int) -> int:
    """Smallest registered pad covering ``n`` (callers chunk at _PAD_MAX)."""
    return 1 << max(0, n - 1).bit_length()


class BlockAllocator:
    """Refcounted free-list allocator over ``total_blocks`` physical blocks.

    Block 0 is reserved as the null block (block tables are padded with 0;
    its contents are garbage but always masked out by sequence lengths).
    Refcounts exist for prefix caching: a block shared by k sequences (plus
    possibly the prefix cache itself) is freed only when every holder lets
    go.
    """

    def __init__(self, total_blocks: int):
        if total_blocks < 2:
            raise ValueError("need at least 2 blocks (0 is reserved)")
        self.total = total_blocks
        self._free: List[int] = list(range(total_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"wanted {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block: int) -> None:
        if block not in self._ref:
            raise ValueError(f"incref of unallocated block {block}")
        self._ref[block] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; a block returns to the free list
        when its last reference goes."""
        for b in blocks:
            if b == 0:
                raise ValueError("block 0 is reserved")
            if b not in self._ref:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)


@dataclasses.dataclass
class SeqAllocation:
    """Host bookkeeping for one running sequence."""

    seq_id: int
    blocks: List[int]
    n_tokens: int = 0

    def table(self, blocks_per_seq: int) -> np.ndarray:
        t = np.zeros((blocks_per_seq,), np.int32)
        t[: len(self.blocks)] = self.blocks
        return t


class PagedKVCache:
    """Device block pool + per-sequence block accounting.

    ``kv`` is a pytree: per layer ``{"k": [N, Bs, Hkv, Dh], "v": ...}``.
    The jitted model paths update it functionally (donated) via
    :func:`write_prefill` / :func:`write_decode` in ``engine.runner``.
    """

    def __init__(self, n_layers: int, n_kv_heads: int, head_dim: int,
                 total_blocks: int, block_size: int, blocks_per_seq: int,
                 dtype=jnp.bfloat16, sharding=None,
                 enable_prefix_caching: bool = False, tier=None,
                 quant: bool = False):
        self.n_layers = n_layers
        self.block_size = block_size
        self.blocks_per_seq = blocks_per_seq
        #: int8 KV pool (SHAI_KV_QUANT): blocks live as int8 with ONE f32
        #: scale per (block, kv head) riding alongside ("ks"/"vs") —
        #: ~2x blocks per HBM byte, priced through the SAME pool_bytes
        #: seam the HBM ledger and admission gate already read
        self.quant = quant
        self.allocator = BlockAllocator(total_blocks)
        # automatic prefix caching (the vLLM knob): full blocks are
        # content-addressed by a chain hash over their tokens; the cache
        # holds one reference per cached block and evicts LRU when the
        # allocator runs dry. Registered blocks are never written again
        # (prefill writes only a sequence's OWN fresh blocks; decode writes
        # past the prompt), so sharing is read-only by construction.
        self.prefix_caching = enable_prefix_caching
        self._hash2block: Dict[int, int] = {}
        self._block2hash: Dict[int, int] = {}
        self._lru: Dict[int, None] = {}     # insertion-ordered hash -> None
        # chain links for leaf-first eviction: evicting a chain HEAD first
        # would strand its cached descendants (lookups break at the missing
        # head while the tail still pins blocks)
        self._parent: Dict[int, int] = {}
        self._nchild: Dict[int, int] = {}
        shape = (total_blocks, block_size, n_kv_heads, head_dim)
        sc_shape = (total_blocks, n_kv_heads)

        def zeros(name: str, shp, dt) -> jax.Array:
            z = jnp.zeros(shp, dt)
            if sharding is not None:
                # tensor-parallel pool: split on the kv-head axis so each tp
                # rank owns its heads' blocks (sharding: {"k": NS, "v": NS})
                z = jax.device_put(z, sharding[name])
            return z

        block_dt = jnp.int8 if quant else dtype
        self.kv = [{"k": zeros("k", shape, block_dt),
                    "v": zeros("v", shape, block_dt)}
                   for _ in range(n_layers)]
        if quant:
            for lay in self.kv:
                lay["ks"] = zeros("ks", sc_shape, jnp.float32)
                lay["vs"] = zeros("vs", sc_shape, jnp.float32)
        self._seqs: Dict[int, SeqAllocation] = {}
        self.total_blocks = total_blocks
        # fixed device allocation: price it ONCE (the HBM ledger reads it
        # every engine step — a per-step re-sum is hot-loop host work).
        # Every leaf counts, scale arrays included: shai_hbm_kv_pool_bytes
        # must show the REAL int8 pool cost, not the bf16 one
        self._pool_bytes = sum(int(a.nbytes)
                               for lay in self.kv for a in lay.values())
        # telemetry counters (obs.steploop reads them through the engine):
        # speculative rollbacks give reserved tokens/blocks back via shrink —
        # a high rollback rate is the "drafter wasting pool headroom" signal
        self.rollback_tokens = 0
        self.rollback_calls = 0
        self.rollback_blocks = 0
        # copy-on-write fan-out (SHAI_KV_COW): forks share blocks via the
        # same refcounts prefix caching uses; the first divergent write
        # into a shared partial tail block pays ONE device block copy
        self.cow_forks = 0
        self.cow_copies = 0
        # one jitted whole-block copy per (shape, dtype) leaf; src/dst ride
        # as DATA so every fork reuses the same compiled copy
        self._cow_copy = jax.jit(
            lambda arr, s, d: arr.at[d].set(arr[s]), donate_argnums=(0,))
        # host KV tier (kvtier/): eviction demotes cached blocks to a
        # bounded host-RAM pool instead of destroying them; admission
        # misses fall through to it and restore via a scatter-write
        self.tier = None
        self._tier_gather = None
        self._tier_restore = None
        if tier is not None:
            self.attach_tier(tier)

    # -- prefix cache -------------------------------------------------------

    @staticmethod
    def _chain_hashes(tokens, block_size: int):
        """Chain hash per FULL block: h_i commits to every token up to and
        including block i, so equal hashes mean equal prefixes.

        Blake2b-based, NOT Python's builtin hash: since the kvnet
        transport (``GET /kv/blocks``) keys blocks by these hashes ACROSS
        pods, the value must be a stable function of the tokens alone —
        the builtin tuple hash is CPython-build/version-dependent, and a
        staggered image rollout across interpreter versions would make
        every cross-pod handoff silently miss. 64-bit signed (fits the
        frame codec's ``<q`` and the int keys everywhere else)."""
        import hashlib

        out = []
        h = 0x5351  # fixed chain seed
        n_full = len(tokens) // block_size
        for i in range(n_full):
            m = hashlib.blake2b(digest_size=8)
            m.update(h.to_bytes(8, "little", signed=True))
            m.update(np.asarray(tokens[i * block_size:(i + 1) * block_size],
                                dtype="<i8").tobytes())
            h = int.from_bytes(m.digest(), "little", signed=True)
            out.append(h)
        return out

    def prefix_hashes(self, tokens) -> List[int]:
        """The prompt's full-block chain hashes — computed ONCE per
        admission attempt and shared by :meth:`cached_prefix`,
        :meth:`tier_prefix_len`, and :meth:`restore_prefix` (hashing every
        token is pure-Python work on the per-step admission path)."""
        if not self.prefix_caching:
            return []
        return self._chain_hashes(tokens, self.block_size)

    def cached_prefix(self, tokens, hashes: Optional[List[int]] = None
                      ) -> List[int]:
        """Longest run of cached blocks matching the prompt's full blocks."""
        if not self.prefix_caching:
            return []
        blocks = []
        for h in (hashes if hashes is not None
                  else self._chain_hashes(tokens, self.block_size)):
            b = self._hash2block.get(h)
            if b is None:
                break
            blocks.append(b)
            self._lru.pop(h, None)      # touch: most-recently-used
            self._lru[h] = None
        return blocks

    def register_prefix(self, tokens, blocks: List[int]) -> None:
        """Publish a prefilled prompt's full blocks for future reuse; the
        cache takes one reference per newly-registered block."""
        if not self.prefix_caching:
            return
        prev = None
        for h, b in zip(self._chain_hashes(tokens, self.block_size), blocks):
            if h in self._hash2block:
                prev = h
                continue  # an identical block is already published
            if b in self._block2hash:
                prev = h
                continue  # this physical block already backs another hash
            self._hash2block[h] = b
            self._block2hash[b] = h
            self.allocator.incref(b)
            self._lru[h] = None
            if prev is not None and prev in self._hash2block:
                self._parent[h] = prev
                self._nchild[prev] = self._nchild.get(prev, 0) + 1
            prev = h

    @property
    def n_evictable(self) -> int:
        """Cached blocks held ONLY by the cache (refcount 1) — reclaimable."""
        return sum(1 for h, b in self._hash2block.items()
                   if self.allocator.refcount(b) == 1)

    @property
    def n_available(self) -> int:
        """Free blocks plus what eviction could reclaim — the admission
        gate's denominator. Tier-aware by construction: with a host tier
        attached, evicting a cached block demotes its contents instead of
        destroying them, so counting evictable blocks as available no
        longer prices reclaimed cache hits as lost prefill work (the
        admission gate still sheds earlier when the HOST pool itself
        saturates — ``resilience.admission``)."""
        return self.allocator.n_free + self.n_evictable

    def _evict(self, n: int) -> int:
        """Drop up to ``n`` LRU cache-only blocks, LEAVES first — a chain
        must shed from the tail or its survivors become unreachable.

        With a host tier attached, eviction is a DEMOTION: the dropped
        blocks' KV is gathered (one dispatch, before any re-allocation can
        overwrite them) and handed to the tier, where a later admission
        miss can restore it instead of re-running prefill."""
        dropped = 0
        demoted: List[Tuple[int, int]] = []
        progress = True
        while dropped < n and progress:
            progress = False
            for h in list(self._lru):
                if dropped >= n:
                    break
                b = self._hash2block[h]
                if self.allocator.refcount(b) != 1:
                    continue  # still shared by a live sequence
                if self._nchild.get(h, 0):
                    continue  # cached descendants would be stranded
                del self._hash2block[h]
                del self._block2hash[b]
                del self._lru[h]
                parent = self._parent.pop(h, None)
                if parent is not None:
                    self._nchild[parent] -= 1
                    if not self._nchild[parent]:
                        del self._nchild[parent]
                if self.tier is not None and self.tier.accepts(h):
                    demoted.append((h, b))
                self.allocator.free([b])
                dropped += 1
                progress = True
        if demoted:
            # the gather dispatches BEFORE the caller's allocation can
            # write the freed blocks (dispatch order is data order); its
            # outputs are fresh buffers, safe to materialize later
            self._demote(demoted)
        return dropped

    def _alloc(self, n: int) -> List[int]:
        short = n - self.allocator.n_free
        if short > 0:
            self._evict(short)
        return self.allocator.alloc(n)

    # -- host KV tier (kvtier/) --------------------------------------------

    def attach_tier(self, tier) -> None:
        """Wire a :class:`~..kvtier.pool.HostKVTier` behind the prefix
        cache and prime the jitted movers against the live pool — the
        closed pad-size set compiles HERE, never on a post-ready request
        (the cold-graph-behind-the-LB discipline)."""
        from ..kvtier.restore import make_tier_gather, make_tier_restore

        self.tier = tier
        self._tier_gather = make_tier_gather(quant=self.quant)
        self._tier_restore = make_tier_restore(quant=self.quant)
        lay0 = self.kv[0]
        shape = lay0["k"].shape[1:]
        dt = lay0["k"].dtype
        for pad in _PAD_SIZES:
            idx = jnp.zeros((pad,), jnp.int32)
            self._tier_gather(self.kv, idx)
            zeros = jnp.zeros((pad,) + shape, dt)
            # priming writes zeros into reserved block 0 — garbage there
            # is allowed by contract (tables mask it out)
            if self.quant:
                sc0 = jnp.zeros((pad,) + lay0["ks"].shape[1:], jnp.float32)
                (lay0["k"], lay0["v"], lay0["ks"],
                 lay0["vs"]) = self._tier_restore(
                    lay0["k"], lay0["v"], lay0["ks"], lay0["vs"], idx,
                    zeros, zeros, sc0, sc0)
            else:
                lay0["k"], lay0["v"] = self._tier_restore(
                    lay0["k"], lay0["v"], idx, zeros, zeros)

    def _demote(self, pairs: Sequence[Tuple[int, int]]) -> None:
        """Copy evicted blocks' KV out to the host tier: one batched
        gather per <=``_PAD_MAX`` chunk, handed to the tier (async mode
        enqueues the device buffers; the copy-out worker pays the
        transfer). Failures degrade to plain eviction, never raise."""
        tier = self.tier
        try:
            i = 0
            while i < len(pairs):
                grp = list(pairs[i:i + _PAD_MAX])
                n = len(grp)
                idx = np.zeros((_pad_size(n),), np.int32)
                idx[:n] = [b for _, b in grp]
                # quantized pools gather (k, v, ks, vs) in ONE dispatch —
                # the scales ride to the host next to their int8 blocks
                arrays = self._tier_gather(self.kv, jnp.asarray(idx))
                tier.store_batch([h for h, _ in grp], *arrays, n)
                i += n
        except Exception:
            log.warning("kv tier demotion failed; blocks evicted without "
                        "copy", exc_info=True)
            tier.count_error()

    def tier_prefix_len(self, hashes: List[int], from_block: int) -> int:
        """How many full blocks past ``from_block`` the host tier could
        restore for this prompt — the admission ladder's fall-through
        probe when :meth:`cached_prefix` stops short. ``hashes`` is the
        caller's :meth:`prefix_hashes` result (hashed once, shared)."""
        if self.tier is None or from_block >= len(hashes):
            return 0
        return self.tier.probe_run(hashes[from_block:])

    def restore_prefix(self, hashes: List[int], from_block: int, take: int,
                       pin: Sequence[int] = ()) -> List[int]:
        """Swap up to ``take`` host-tier blocks back into the device pool
        and register them as prefix-cache entries (refcount 1, the
        cache's own reference — exactly the state :meth:`register_prefix`
        leaves). Returns the restored device block ids; any shortfall
        (raced host eviction, transfer failure, dry pool) degrades to
        recompute for the uncovered remainder, never to an error.

        ``pin``: the device-cached run the caller is about to share —
        increfed around the allocation so the restore can never evict the
        very blocks it is extending."""
        if self.tier is None or take <= 0:
            return []
        run = self.tier.get_run(hashes[from_block:from_block + take])
        if not run:
            return []
        for b in pin:
            self.allocator.incref(b)
        try:
            try:
                blocks = self._alloc(len(run))
            except MemoryError:
                return []
            try:
                self._tier_write(blocks, run)
            except Exception:
                log.warning("kv tier restore failed; falling back to "
                            "recompute", exc_info=True)
                self.allocator.free(blocks)
                self.tier.count_error()
                return []
        finally:
            if pin:
                # pinned blocks are cache-registered (refcount >= 2 while
                # pinned), so this decref can never free them
                self.allocator.free(list(pin))
        prev = hashes[from_block - 1] if from_block > 0 else None
        if prev is not None and prev not in self._hash2block:
            prev = None
        for ent, b in zip(run, blocks):
            h = ent[0]
            self._hash2block[h] = b
            self._block2hash[b] = h
            self._lru[h] = None
            if prev is not None:
                self._parent[h] = prev
                self._nchild[prev] = self._nchild.get(prev, 0) + 1
            prev = h
        self.tier.count_restored(len(blocks))
        return blocks

    def _tier_write(self, blocks: List[int], run: List[Tuple]) -> None:
        """ONE jitted scatter-write per layer per <=``_PAD_MAX`` chunk:
        the restored blocks' host k/v (and the scale rows of a quantized
        pool) goes back into the pool rows ``blocks`` (padding rows target
        reserved block 0). Pure copies — a restored block is byte-exact."""
        i = 0
        while i < len(blocks):
            grp = blocks[i:i + _PAD_MAX]
            ent = run[i:i + _PAD_MAX]
            n = len(grp)
            pad = _pad_size(n)
            idx = np.zeros((pad,), np.int32)
            idx[:n] = grp
            # entry arrays are [n_layers, <block dims>]; stack per layer —
            # slot 0/1 = k/v blocks, slots 2/3 = the quantized scales
            n_arr = len(ent[0]) - 1
            bufs = []
            for ai in range(n_arr):
                per = ent[0][1 + ai].shape[1:]
                buf = np.zeros((self.n_layers, pad) + per,
                               ent[0][1 + ai].dtype)
                for j, e in enumerate(ent):
                    buf[:, j] = e[1 + ai]
                bufs.append(buf)
            idx_dev = jnp.asarray(idx)
            for li, lay in enumerate(self.kv):
                host = [jnp.asarray(b[li]) for b in bufs]
                if self.quant:
                    (lay["k"], lay["v"], lay["ks"],
                     lay["vs"]) = self._tier_restore(
                        lay["k"], lay["v"], lay["ks"], lay["vs"],
                        idx_dev, *host)
                else:
                    lay["k"], lay["v"] = self._tier_restore(
                        lay["k"], lay["v"], idx_dev, *host)
            i += n

    def demote_prompt_run(self, seq_id: int, prompt_ids) -> int:
        """Prefill-role handoff (kvnet): copy the sequence's full prompt
        blocks into the host tier WITHOUT evicting them from the device —
        the block data is gathered positionally from the sequence's own
        allocation (``admit`` lays blocks out in prompt order), so this
        works whatever admission path built it. Called by the engine at
        request finish, BEFORE release, so a peer decode pod can pull the
        run over ``GET /kv/blocks`` the moment the handoff returns.
        Returns the prompt's full-block count (the handoff's
        ``hashes_len``); failures degrade to recompute-on-the-peer via the
        ``_demote`` contract, never raise."""
        if self.tier is None or not self.prefix_caching:
            return 0
        alloc = self._seqs.get(seq_id)
        if alloc is None:
            return 0
        # NO re-hash here — this runs inside the step loop at every
        # finish on a prefill pod. Every prefill-role admission path has
        # register_prefix'ed the prompt's full blocks, so each block's
        # hash is one _block2hash lookup; an unregistered block (a
        # duplicate prompt whose identical blocks were published under
        # the FIRST copy's physical blocks) ends the walk — harmless,
        # the content-addressed tier already holds that run via the
        # first copy's demotions.
        n_full = len(prompt_ids) // self.block_size
        pairs: List[Tuple[int, int]] = []
        n_run = 0
        for b in alloc.blocks[:n_full]:
            h = self._block2hash.get(b)
            if h is None:
                break
            n_run += 1
            if self.tier.accepts(h):
                pairs.append((h, b))
        if pairs:
            self._demote(pairs)
        return n_run

    def demote_token_run(self, seq_id: int,
                         tokens) -> Tuple[int, List[int]]:
        """Live-migration bank (kvnet.migrate): copy the sequence's full
        blocks over ``tokens`` — prompt AND generated alike — into the
        host tier without evicting them from the device. Unlike
        :meth:`demote_prompt_run` (the per-finish prefill-handoff hot
        path, which walks only already-registered blocks), this PUBLISHES
        the run first: a mid-decode sequence's generated full blocks have
        never been content-addressed, and the migration manifest needs
        their chain hashes on the wire. Migration is a drain-time event,
        so the extra hash pass is off every hot path. Returns
        ``(n_run, hashes[:n_run])`` — the leading run actually banked;
        failures degrade through the ``_demote`` contract (the peer
        recomputes the shortfall), never raise."""
        if self.tier is None or not self.prefix_caching:
            return 0, []
        alloc = self._seqs.get(seq_id)
        if alloc is None:
            return 0, []
        hashes = self.prefix_hashes(tokens)
        if not hashes:
            return 0, []
        # publish prompt+generated full blocks (register_prefix no-ops
        # per-block where an identical block is already cached)
        self.register_prefix(tokens, alloc.blocks)
        pairs: List[Tuple[int, int]] = []
        n_run = 0
        for h, b in zip(hashes, alloc.blocks):
            # a duplicate prompt's blocks may be registered under ANOTHER
            # physical block — content-addressing means the tier run is
            # still intact through that first copy, keep walking by hash
            if self._hash2block.get(h) is None:
                break
            n_run += 1
            src = self._hash2block[h]
            if self.tier.accepts(h):
                pairs.append((h, src))
        if pairs:
            self._demote(pairs)
        return n_run, hashes[:n_run]

    def offload_preempt(self, tokens, seq_id: int) -> None:
        """Preemption offload: publish the victim's full blocks to the
        prefix cache (free — one incref per block) so re-admission reuses
        them directly while they survive, and pool pressure demotes them
        to the host tier through the eviction hook instead of destroying
        prefill+decode work. Only meaningful with a tier attached — the
        pre-tier engine keeps its exact preemption accounting."""
        if self.tier is None or not self.prefix_caching:
            return
        alloc = self._seqs.get(seq_id)
        if alloc is None:
            return
        self.register_prefix(tokens, alloc.blocks)

    # -- host-side sequence lifecycle --------------------------------------

    def can_admit(self, n_tokens: int) -> bool:
        return self._blocks_needed(n_tokens) <= self.n_available

    def admit(self, seq_id: int, n_tokens: int,
              reuse_blocks: Optional[List[int]] = None) -> SeqAllocation:
        """Allocate blocks to cover ``n_tokens`` prompt tokens.

        ``reuse_blocks``: cached prefix blocks to share (prefix caching) —
        they are increfed, and only the remainder is freshly allocated.
        """
        if seq_id in self._seqs:
            raise ValueError(f"seq {seq_id} already admitted")
        reuse = list(reuse_blocks or [])
        need = self._blocks_needed(n_tokens) - len(reuse)
        assert need >= 0, "reuse longer than the prompt"
        # pin the reused blocks FIRST: at refcount 2 they are not evictable,
        # so the allocation below can never evict what we are about to share
        for b in reuse:
            self.allocator.incref(b)
        try:
            fresh = self._alloc(need)
        except MemoryError:
            self.allocator.free(reuse)
            raise
        alloc = SeqAllocation(seq_id, reuse + fresh, n_tokens)
        self._seqs[seq_id] = alloc
        return alloc

    def fork_sequence(self, parent_id: int, child_id: int) -> SeqAllocation:
        """Copy-on-write fan-out seam (SHAI_KV_COW): admit ``child_id``
        sharing every block of ``parent_id`` (one incref each — the same
        refcounts prefix caching stacks on). Divergence is lazy: the first
        write into a shared partial tail block forks a private copy inside
        :meth:`extend`. Full shared blocks are never written again (prefill
        writes only fresh blocks, decode writes past ``n_tokens`` — the
        read-only-sharing contract above), so only the tail can ever need
        the copy; release/eviction need no special casing because a forked
        block simply carries refcount >= 2 until each holder lets go."""
        if child_id in self._seqs:
            raise ValueError(f"seq {child_id} already admitted")
        parent = self._seqs[parent_id]
        for b in parent.blocks:
            self.allocator.incref(b)
        alloc = SeqAllocation(child_id, list(parent.blocks), parent.n_tokens)
        self._seqs[child_id] = alloc
        self.cow_forks += 1
        return alloc

    def _cow_block(self, alloc: SeqAllocation, idx: int) -> None:
        """Fork a private copy of shared block ``alloc.blocks[idx]`` before
        the first divergent write lands in it. Allocates BEFORE dropping
        the shared reference (a MemoryError here leaves the fork intact for
        the caller's preempt-and-retry ladder), copies every pool leaf —
        int8 blocks and their scale rows byte-exactly — then swaps the
        sequence's table entry. The LAST holder never copies: its refcount
        is 1 by then, so n writers pay exactly n - 1 copies."""
        src = alloc.blocks[idx]
        [dst] = self._alloc(1)
        s = jnp.asarray(src, jnp.int32)
        d = jnp.asarray(dst, jnp.int32)
        for lay in self.kv:
            for name in list(lay):
                lay[name] = self._cow_copy(lay[name], s, d)
        self.allocator.free([src])
        alloc.blocks[idx] = dst
        self.cow_copies += 1

    def _cow_pending(self, alloc: SeqAllocation) -> bool:
        """True when growing ``alloc`` would write into a partial tail
        block some OTHER holder still references — the one block layout
        where extend must fork first."""
        idx = alloc.n_tokens // self.block_size
        return (alloc.n_tokens % self.block_size != 0
                and idx < len(alloc.blocks)
                and self.allocator.refcount(alloc.blocks[idx]) > 1)

    def blocks_to_extend(self, seq_id: int, n_new: int = 1) -> int:
        """Fresh blocks :meth:`extend` would need to grow ``seq_id`` by
        ``n_new`` tokens (0 when the current tail block still has room).

        The async decode pipeline prices a whole step's growth through this
        BEFORE touching the allocator: the steady (lookahead) path must
        never trigger a recompute-preemption mid-dispatch — when the summed
        need exceeds ``n_available`` it flushes and lets the lock-step
        grow-with-preemption path handle the pressure instead. A pending
        copy-on-write fork (shared partial tail about to be written) prices
        its +1 copy block HERE so every caller stays consistent with what
        extend will actually allocate.
        """
        alloc = self._seqs[seq_id]
        need = max(0, self._blocks_needed(alloc.n_tokens + n_new)
                   - len(alloc.blocks))
        if n_new > 0 and self._cow_pending(alloc):
            need += 1
        return need

    def extend(self, seq_id: int, n_new: int = 1) -> SeqAllocation:
        """Grow a sequence by ``n_new`` tokens, allocating blocks as needed.

        When the write range opens inside a shared partial tail block (a
        :meth:`fork_sequence` sibling that is about to diverge), the block
        is copy-on-write forked first — only that one block can ever be
        both shared and written (see the read-only-sharing contract)."""
        alloc = self._seqs[seq_id]
        if n_new > 0 and self._cow_pending(alloc):
            self._cow_block(alloc, alloc.n_tokens // self.block_size)
        need = self._blocks_needed(alloc.n_tokens + n_new) - len(alloc.blocks)
        if need > 0:
            if len(alloc.blocks) + need > self.blocks_per_seq:
                raise MemoryError(f"seq {seq_id} exceeds max_model_len")
            alloc.blocks.extend(self._alloc(need))
        alloc.n_tokens += n_new
        return alloc

    def shrink(self, seq_id: int, n_remove: int) -> SeqAllocation:
        """Roll back the last ``n_remove`` reserved tokens, freeing trailing
        blocks the shorter sequence no longer needs.

        Speculative decoding reserves ``1 + k`` tokens optimistically before
        verification; rejected drafts give their reservation back here so a
        partially-accepted step can't leak pool blocks. Only freshly
        allocated decode-tail blocks are ever in the rollback range —
        prefix-cache-shared blocks live at the FRONT of the allocation
        (``admit`` places ``reuse + fresh``) and a sequence never shrinks
        below its already-committed token count, so a shared block's
        refcount is never touched from here.
        """
        alloc = self._seqs[seq_id]
        if n_remove <= 0:
            return alloc
        assert n_remove <= alloc.n_tokens, "shrink below zero tokens"
        alloc.n_tokens -= n_remove
        self.rollback_tokens += n_remove
        self.rollback_calls += 1
        keep = self._blocks_needed(alloc.n_tokens)
        if keep < len(alloc.blocks):
            tail = alloc.blocks[keep:]
            del alloc.blocks[keep:]
            self.allocator.free(tail)
            self.rollback_blocks += len(tail)
        return alloc

    def release(self, seq_id: int) -> None:
        alloc = self._seqs.pop(seq_id)
        self.allocator.free(alloc.blocks)  # cached blocks survive (cache ref)

    def seq(self, seq_id: int) -> SeqAllocation:
        return self._seqs[seq_id]

    # -- HBM ledger feed (obs.hbm) -----------------------------------------

    @property
    def pool_bytes(self) -> int:
        """Total device bytes of the preallocated KV pool (all layers;
        priced once at construction — the pool never resizes)."""
        return self._pool_bytes

    @property
    def used_bytes(self) -> float:
        """Logical bytes of allocated (non-free) blocks — the pool is a
        fixed device allocation, so block-level pressure shows up here,
        not in ``pool_bytes``. The reserved null block 0 is excluded: an
        empty pool reads 0, matching :meth:`leaked_blocks`' accounting."""
        if self.total_blocks <= 0:
            return 0.0
        used = (self.total_blocks - 1) - self.allocator.n_free
        return self.pool_bytes * (used / self.total_blocks)

    @property
    def leaked_blocks(self) -> int:
        """Allocated blocks no live holder explains: not referenced by any
        admitted sequence nor by the prefix cache. Always 0 in a correct
        engine — a sequence's natural KV growth is *held* growth — so this
        is the exact KV-leak signal the HBM ledger's drift detector
        tracks (a raw used-block count would read every decoding sequence
        as a leak)."""
        held = set()
        for a in self._seqs.values():
            held.update(a.blocks)
        held.update(self._block2hash.keys())
        used = (self.total_blocks - 1) - self.allocator.n_free  # 0 reserved
        return max(0, used - len(held))

    @property
    def leaked_bytes(self) -> float:
        if self.total_blocks <= 0:
            return 0.0
        return self.pool_bytes * (self.leaked_blocks / self.total_blocks)

    @property
    def active(self) -> List[int]:
        return sorted(self._seqs)

    def _blocks_needed(self, n_tokens: int) -> int:
        return max(1, -(-n_tokens // self.block_size))
