"""Paged KV cache: device block pool + host-side block allocator.

The reference gets paged KV from vLLM's neuron fork (``block_size: 4096``,
reference ``cova/mllama-32-11b-vllm-trn1-config.yaml:16``). TPU-natively the
pool is one device array per layer ``[num_blocks, block_size, n_kv, head_dim]``
— block tables are *data* (int32 arrays), so one compiled executable serves
any allocation pattern; only bucket shapes trigger compiles.

Allocation is host-side and O(1) per block (free list). The device never
sees fragmentation: gathers go through block tables.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class BlockAllocator:
    """Refcounted free-list allocator over ``total_blocks`` physical blocks.

    Block 0 is reserved as the null block (block tables are padded with 0;
    its contents are garbage but always masked out by sequence lengths).
    Refcounts exist for prefix caching: a block shared by k sequences (plus
    possibly the prefix cache itself) is freed only when every holder lets
    go.
    """

    def __init__(self, total_blocks: int):
        if total_blocks < 2:
            raise ValueError("need at least 2 blocks (0 is reserved)")
        self.total = total_blocks
        self._free: List[int] = list(range(total_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"wanted {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block: int) -> None:
        if block not in self._ref:
            raise ValueError(f"incref of unallocated block {block}")
        self._ref[block] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; a block returns to the free list
        when its last reference goes."""
        for b in blocks:
            if b == 0:
                raise ValueError("block 0 is reserved")
            if b not in self._ref:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)


@dataclasses.dataclass
class SeqAllocation:
    """Host bookkeeping for one running sequence."""

    seq_id: int
    blocks: List[int]
    n_tokens: int = 0

    def table(self, blocks_per_seq: int) -> np.ndarray:
        t = np.zeros((blocks_per_seq,), np.int32)
        t[: len(self.blocks)] = self.blocks
        return t


class PagedKVCache:
    """Device block pool + per-sequence block accounting.

    ``kv`` is a pytree: per layer ``{"k": [N, Bs, Hkv, Dh], "v": ...}``.
    The jitted model paths update it functionally (donated) via
    :func:`write_prefill` / :func:`write_decode` in ``engine.runner``.
    """

    def __init__(self, n_layers: int, n_kv_heads: int, head_dim: int,
                 total_blocks: int, block_size: int, blocks_per_seq: int,
                 dtype=jnp.bfloat16, sharding=None,
                 enable_prefix_caching: bool = False):
        self.n_layers = n_layers
        self.block_size = block_size
        self.blocks_per_seq = blocks_per_seq
        self.allocator = BlockAllocator(total_blocks)
        # automatic prefix caching (the vLLM knob): full blocks are
        # content-addressed by a chain hash over their tokens; the cache
        # holds one reference per cached block and evicts LRU when the
        # allocator runs dry. Registered blocks are never written again
        # (prefill writes only a sequence's OWN fresh blocks; decode writes
        # past the prompt), so sharing is read-only by construction.
        self.prefix_caching = enable_prefix_caching
        self._hash2block: Dict[int, int] = {}
        self._block2hash: Dict[int, int] = {}
        self._lru: Dict[int, None] = {}     # insertion-ordered hash -> None
        # chain links for leaf-first eviction: evicting a chain HEAD first
        # would strand its cached descendants (lookups break at the missing
        # head while the tail still pins blocks)
        self._parent: Dict[int, int] = {}
        self._nchild: Dict[int, int] = {}
        shape = (total_blocks, block_size, n_kv_heads, head_dim)

        def zeros(name: str) -> jax.Array:
            z = jnp.zeros(shape, dtype)
            if sharding is not None:
                # tensor-parallel pool: split on the kv-head axis so each tp
                # rank owns its heads' blocks (sharding: {"k": NS, "v": NS})
                z = jax.device_put(z, sharding[name])
            return z

        self.kv = [{"k": zeros("k"), "v": zeros("v")} for _ in range(n_layers)]
        self._seqs: Dict[int, SeqAllocation] = {}
        self.total_blocks = total_blocks
        # fixed device allocation: price it ONCE (the HBM ledger reads it
        # every engine step — a per-step re-sum is hot-loop host work)
        self._pool_bytes = sum(int(a["k"].nbytes) + int(a["v"].nbytes)
                               for a in self.kv)
        # telemetry counters (obs.steploop reads them through the engine):
        # speculative rollbacks give reserved tokens/blocks back via shrink —
        # a high rollback rate is the "drafter wasting pool headroom" signal
        self.rollback_tokens = 0
        self.rollback_calls = 0
        self.rollback_blocks = 0

    # -- prefix cache -------------------------------------------------------

    @staticmethod
    def _chain_hashes(tokens, block_size: int):
        """Chain hash per FULL block: h_i commits to every token up to and
        including block i, so equal hashes mean equal prefixes."""
        out = []
        h = 0x5351  # fixed seed: process-local python hashes suffice
        for i in range(len(tokens) // block_size):
            h = hash((h, tuple(tokens[i * block_size:(i + 1) * block_size])))
            out.append(h)
        return out

    def cached_prefix(self, tokens) -> List[int]:
        """Longest run of cached blocks matching the prompt's full blocks."""
        if not self.prefix_caching:
            return []
        blocks = []
        for h in self._chain_hashes(tokens, self.block_size):
            b = self._hash2block.get(h)
            if b is None:
                break
            blocks.append(b)
            self._lru.pop(h, None)      # touch: most-recently-used
            self._lru[h] = None
        return blocks

    def register_prefix(self, tokens, blocks: List[int]) -> None:
        """Publish a prefilled prompt's full blocks for future reuse; the
        cache takes one reference per newly-registered block."""
        if not self.prefix_caching:
            return
        prev = None
        for h, b in zip(self._chain_hashes(tokens, self.block_size), blocks):
            if h in self._hash2block:
                prev = h
                continue  # an identical block is already published
            if b in self._block2hash:
                prev = h
                continue  # this physical block already backs another hash
            self._hash2block[h] = b
            self._block2hash[b] = h
            self.allocator.incref(b)
            self._lru[h] = None
            if prev is not None and prev in self._hash2block:
                self._parent[h] = prev
                self._nchild[prev] = self._nchild.get(prev, 0) + 1
            prev = h

    @property
    def n_evictable(self) -> int:
        """Cached blocks held ONLY by the cache (refcount 1) — reclaimable."""
        return sum(1 for h, b in self._hash2block.items()
                   if self.allocator.refcount(b) == 1)

    @property
    def n_available(self) -> int:
        """Free blocks plus what eviction could reclaim — the admission
        gate's denominator."""
        return self.allocator.n_free + self.n_evictable

    def _evict(self, n: int) -> int:
        """Drop up to ``n`` LRU cache-only blocks, LEAVES first — a chain
        must shed from the tail or its survivors become unreachable."""
        dropped = 0
        progress = True
        while dropped < n and progress:
            progress = False
            for h in list(self._lru):
                if dropped >= n:
                    break
                b = self._hash2block[h]
                if self.allocator.refcount(b) != 1:
                    continue  # still shared by a live sequence
                if self._nchild.get(h, 0):
                    continue  # cached descendants would be stranded
                del self._hash2block[h]
                del self._block2hash[b]
                del self._lru[h]
                parent = self._parent.pop(h, None)
                if parent is not None:
                    self._nchild[parent] -= 1
                    if not self._nchild[parent]:
                        del self._nchild[parent]
                self.allocator.free([b])
                dropped += 1
                progress = True
        return dropped

    def _alloc(self, n: int) -> List[int]:
        short = n - self.allocator.n_free
        if short > 0:
            self._evict(short)
        return self.allocator.alloc(n)

    # -- host-side sequence lifecycle --------------------------------------

    def can_admit(self, n_tokens: int) -> bool:
        return self._blocks_needed(n_tokens) <= self.n_available

    def admit(self, seq_id: int, n_tokens: int,
              reuse_blocks: Optional[List[int]] = None) -> SeqAllocation:
        """Allocate blocks to cover ``n_tokens`` prompt tokens.

        ``reuse_blocks``: cached prefix blocks to share (prefix caching) —
        they are increfed, and only the remainder is freshly allocated.
        """
        if seq_id in self._seqs:
            raise ValueError(f"seq {seq_id} already admitted")
        reuse = list(reuse_blocks or [])
        need = self._blocks_needed(n_tokens) - len(reuse)
        assert need >= 0, "reuse longer than the prompt"
        # pin the reused blocks FIRST: at refcount 2 they are not evictable,
        # so the allocation below can never evict what we are about to share
        for b in reuse:
            self.allocator.incref(b)
        try:
            fresh = self._alloc(need)
        except MemoryError:
            self.allocator.free(reuse)
            raise
        alloc = SeqAllocation(seq_id, reuse + fresh, n_tokens)
        self._seqs[seq_id] = alloc
        return alloc

    def blocks_to_extend(self, seq_id: int, n_new: int = 1) -> int:
        """Fresh blocks :meth:`extend` would need to grow ``seq_id`` by
        ``n_new`` tokens (0 when the current tail block still has room).

        The async decode pipeline prices a whole step's growth through this
        BEFORE touching the allocator: the steady (lookahead) path must
        never trigger a recompute-preemption mid-dispatch — when the summed
        need exceeds ``n_available`` it flushes and lets the lock-step
        grow-with-preemption path handle the pressure instead.
        """
        alloc = self._seqs[seq_id]
        return max(0, self._blocks_needed(alloc.n_tokens + n_new)
                   - len(alloc.blocks))

    def extend(self, seq_id: int, n_new: int = 1) -> SeqAllocation:
        """Grow a sequence by ``n_new`` tokens, allocating blocks as needed."""
        alloc = self._seqs[seq_id]
        need = self._blocks_needed(alloc.n_tokens + n_new) - len(alloc.blocks)
        if need > 0:
            if len(alloc.blocks) + need > self.blocks_per_seq:
                raise MemoryError(f"seq {seq_id} exceeds max_model_len")
            alloc.blocks.extend(self._alloc(need))
        alloc.n_tokens += n_new
        return alloc

    def shrink(self, seq_id: int, n_remove: int) -> SeqAllocation:
        """Roll back the last ``n_remove`` reserved tokens, freeing trailing
        blocks the shorter sequence no longer needs.

        Speculative decoding reserves ``1 + k`` tokens optimistically before
        verification; rejected drafts give their reservation back here so a
        partially-accepted step can't leak pool blocks. Only freshly
        allocated decode-tail blocks are ever in the rollback range —
        prefix-cache-shared blocks live at the FRONT of the allocation
        (``admit`` places ``reuse + fresh``) and a sequence never shrinks
        below its already-committed token count, so a shared block's
        refcount is never touched from here.
        """
        alloc = self._seqs[seq_id]
        if n_remove <= 0:
            return alloc
        assert n_remove <= alloc.n_tokens, "shrink below zero tokens"
        alloc.n_tokens -= n_remove
        self.rollback_tokens += n_remove
        self.rollback_calls += 1
        keep = self._blocks_needed(alloc.n_tokens)
        if keep < len(alloc.blocks):
            tail = alloc.blocks[keep:]
            del alloc.blocks[keep:]
            self.allocator.free(tail)
            self.rollback_blocks += len(tail)
        return alloc

    def release(self, seq_id: int) -> None:
        alloc = self._seqs.pop(seq_id)
        self.allocator.free(alloc.blocks)  # cached blocks survive (cache ref)

    def seq(self, seq_id: int) -> SeqAllocation:
        return self._seqs[seq_id]

    # -- HBM ledger feed (obs.hbm) -----------------------------------------

    @property
    def pool_bytes(self) -> int:
        """Total device bytes of the preallocated KV pool (all layers;
        priced once at construction — the pool never resizes)."""
        return self._pool_bytes

    @property
    def used_bytes(self) -> float:
        """Logical bytes of allocated (non-free) blocks — the pool is a
        fixed device allocation, so block-level pressure shows up here,
        not in ``pool_bytes``. The reserved null block 0 is excluded: an
        empty pool reads 0, matching :meth:`leaked_blocks`' accounting."""
        if self.total_blocks <= 0:
            return 0.0
        used = (self.total_blocks - 1) - self.allocator.n_free
        return self.pool_bytes * (used / self.total_blocks)

    @property
    def leaked_blocks(self) -> int:
        """Allocated blocks no live holder explains: not referenced by any
        admitted sequence nor by the prefix cache. Always 0 in a correct
        engine — a sequence's natural KV growth is *held* growth — so this
        is the exact KV-leak signal the HBM ledger's drift detector
        tracks (a raw used-block count would read every decoding sequence
        as a leak)."""
        held = set()
        for a in self._seqs.values():
            held.update(a.blocks)
        held.update(self._block2hash.keys())
        used = (self.total_blocks - 1) - self.allocator.n_free  # 0 reserved
        return max(0, used - len(held))

    @property
    def leaked_bytes(self) -> float:
        if self.total_blocks <= 0:
            return 0.0
        return self.pool_bytes * (self.leaked_blocks / self.total_blocks)

    @property
    def active(self) -> List[int]:
        return sorted(self._seqs)

    def _blocks_needed(self, n_tokens: int) -> int:
        return max(1, -(-n_tokens // self.block_size))
