"""Engine warmup: compile the CLOSED executable set before readiness.

Split from engine.py (VERDICT r3 weak #5): the admission ladder stays in
engine.py; this module owns executable-set warmup. Functions take the engine instance
explicitly — they are the same code paths, re-homed.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

def warm_executables(eng, prefix_lens: Sequence[int] = (0,)) -> int:
    """Compile the engine's CLOSED executable set up front.

    Every (prefill bucket, prefix_len) pair plus every context-bucket
    decode step is built here, so no post-ready request can trigger an
    XLA compile — the reference's warmup-gates-readiness idiom
    (``app/run-sd.py:144-146``) applied to the engine. Returns the number
    of executables compiled.
    """
    n = 0
    kmax = min(max(1, eng.ecfg.max_prefill_batch),
               eng.ecfg.max_num_seqs)
    batch_sizes = []
    k = 1
    while k <= kmax:
        batch_sizes.append(k)
        k *= 2
    for b in eng.buckets.buckets:
        for p in sorted(set(prefix_lens)):
            if p == 0:
                for kb in batch_sizes:
                    eng._prefill_for(b, 0, kb)
                    n += 1
            elif 0 < p < b and eng._cross_kv is None:
                eng._prefill_for(b, p)  # prefix path stays single-seq
                n += 1
    if eng._fused:
        # fused mixed-phase step (SHAI_FUSED_STEP): the decode grid below
        # builds the fused executables, and chunked-prefill continuation
        # and cached admission ride the SAME executables — the rcont
        # ladder has no fused-mode callers, so warming it would compile
        # dead code
        pass
    elif eng._ragged:
        # ragged continuation ladder (SHAI_RAGGED_ATTENTION): the chunk
        # start is DATA, so ONE executable per chunk bucket covers every
        # start offset the bucketed ladder compiled one-by-one — the
        # chunked path and every cached-admission (warm start, bucket)
        # pair alike
        want = set()
        if eng.ecfg.max_model_len > eng.buckets.max:
            want.add(eng.buckets.max)
        if eng.cache.prefix_caching:
            for s in eng._cached_starts():
                for cb in eng.buckets.buckets:
                    if s + cb <= eng.ecfg.max_model_len:
                        want.add(cb)
        for cb in sorted(want):
            if ("rcont", cb) not in eng._prefill:
                eng._cont_for(0, cb)
                n += 1
    else:
        if eng.ecfg.max_model_len > eng.buckets.max:
            # chunked-prefill ladder: one continuation executable per chunk
            # start past the largest bucket (cross engines included — their
            # cont executables carry the cross-args tail)
            C = eng.buckets.max
            start = C
            while start + C <= eng.ecfg.max_model_len:
                eng._cont_for(start // eng.ecfg.block_size)
                n += 1
                start += C
        if eng.cache.prefix_caching:
            # cached-admission ladder: (warm start, chunk bucket) pairs so
            # a cache hit never compiles post-ready (closed set — the SAME
            # _cached_starts list admission picks from)
            for s in eng._cached_starts():
                for cb in eng.buckets.buckets:
                    if s + cb <= eng.ecfg.max_model_len:
                        key = ("cont", s // eng.ecfg.block_size, cb)
                        if key not in eng._prefill:
                            eng._cont_for(s // eng.ecfg.block_size, cb)
                            n += 1
    bb = 1
    batch_buckets = []
    while bb < eng.ecfg.max_num_seqs:
        batch_buckets.append(bb)
        bb *= 2
    batch_buckets.append(eng.ecfg.max_num_seqs)
    for m in eng._ctx_buckets:
        for bb in batch_buckets:
            eng._decode_for(m, bb)
            n += 1
            if eng._drafter is not None:
                # the speculative verify ladder mirrors decode's (ctx,
                # batch) grid: a post-ready verify dispatch must never
                # compile (vanilla decode stays in the set too — the
                # engine falls back to it whenever drafting comes up empty)
                eng._verify_for(m, bb)
                n += 1
    # force compilation (jit is lazy until first call) with null args
    eng._run_warm_calls()
    eng._warmed = True  # cached admission now refuses cold compiles
    # telemetry baseline: every executable built from here on is a
    # bucket-miss recompile (obs counts them; /metrics exposes the total)
    eng.obs.warmed_executables = eng.n_executables
    return n

def _run_warm_calls(eng) -> None:
    ecfg = eng.ecfg
    B, M = ecfg.max_num_seqs, ecfg.blocks_per_seq
    for key, fn in list(eng._prefill.items()):
        if key[0] == "rcont":
            # dynamic-start ragged continuation: the start rides as data
            # (a zero start against the null table writes into reserved
            # block 0 — garbage there is allowed by contract)
            eng.cache.kv, logits = fn(
                eng.params, eng.cache.kv,
                jnp.zeros((1, key[1]), jnp.int32),
                jnp.ones((1,), jnp.int32),
                jnp.zeros((1, M), jnp.int32),
                jnp.zeros((1,), jnp.int32))
            logits.block_until_ready()
            continue
        if key[0] == "cont":
            args = [eng.params, eng.cache.kv,
                    jnp.zeros((1, key[2]), jnp.int32),
                    jnp.ones((1,), jnp.int32),
                    jnp.zeros((1, M), jnp.int32)]
            if eng._cross_kv is not None:
                args += [eng._cross_zeros(1),
                         jnp.zeros((1,), jnp.float32),
                         jnp.full((1,), max(eng.cross_seq_len, 1),
                                  jnp.int32)]
            eng.cache.kv, logits = fn(*args)
            logits.block_until_ready()
            continue
        bucket, P_, K = key
        ids = jnp.zeros((K, bucket - P_), jnp.int32)
        args = [eng.params, eng.cache.kv, ids,
                jnp.ones((K,), jnp.int32), jnp.zeros((K, M), jnp.int32)]
        if P_:
            args.append(jnp.zeros((K, P_, eng.cfg.dim), jnp.float32))
        if eng._cross_kv is not None:
            args += [eng._cross_zeros(K), jnp.zeros((K,), jnp.float32),
                     jnp.full((K,), max(eng.cross_seq_len, 1), jnp.int32)]
        eng.cache.kv, logits = fn(*args)
        logits.block_until_ready()
    for (m, bb), fn in list(eng._decode_fns.items()):
        # async engines warm the feedback variant through the same ladder
        # (one extra pos+1 output rides in *_rest; the donated position
        # buffer here is a warm-only throwaway)
        args = [eng.params, eng.cache.kv, jnp.zeros((bb,), jnp.int32),
                jnp.zeros((bb,), jnp.int32), jnp.zeros((bb, M), jnp.int32),
                jnp.zeros((bb,), bool), jax.random.PRNGKey(0),
                jnp.ones((bb,), jnp.float32), jnp.zeros((bb,), jnp.int32),
                jnp.ones((bb,), jnp.float32)]
        if eng._cross_kv is not None:
            args += [eng._cross_kv, jnp.zeros((bb,), jnp.float32),
                     jnp.zeros((bb,), jnp.int32),
                     jnp.full((bb,), max(eng.cross_seq_len, 1), jnp.int32)]
        eng.cache.kv, nxt, *_rest = fn(*args)
        nxt.block_until_ready()
    for bb, fn in list(eng._fused_fns.items()):
        # fused mixed-phase executables: decode-style null rows plus the
        # 4-arg null chunk window (ntext=1 against the zero table — the
        # write lands in reserved block 0, allowed by contract). tokens
        # and pos must be SEPARATE buffers: the feedback variant donates
        # the position argument.
        args = [eng.params, eng.cache.kv, jnp.zeros((bb,), jnp.int32),
                jnp.zeros((bb,), jnp.int32), jnp.zeros((bb, M), jnp.int32),
                jnp.zeros((bb,), bool), jax.random.PRNGKey(0),
                jnp.ones((bb,), jnp.float32), jnp.zeros((bb,), jnp.int32),
                jnp.ones((bb,), jnp.float32),
                jnp.zeros((1, eng.buckets.max), jnp.int32),
                jnp.ones((1,), jnp.int32),
                jnp.zeros((1, M), jnp.int32),
                jnp.zeros((1,), jnp.int32)]
        eng.cache.kv, nxt, *_rest = fn(*args)
        nxt.block_until_ready()
    K = eng.ecfg.num_speculative_tokens
    for (m, bb), fn in list(eng._verify_fns.items()):
        args = [eng.params, eng.cache.kv,
                jnp.zeros((bb, K + 1), jnp.int32),
                jnp.zeros((bb,), jnp.int32), jnp.zeros((bb, M), jnp.int32),
                jnp.zeros((bb,), bool), jax.random.PRNGKey(0),
                jnp.ones((bb,), jnp.float32), jnp.zeros((bb,), jnp.int32),
                jnp.ones((bb,), jnp.float32)]
        if eng._cross_kv is not None:
            args += [eng._cross_kv, jnp.zeros((bb,), jnp.float32),
                     jnp.zeros((bb,), jnp.int32),
                     jnp.full((bb,), max(eng.cross_seq_len, 1), jnp.int32)]
        eng.cache.kv, o, *_rest = fn(*args)
        o.block_until_ready()
    if eng._cross_embed is not None:  # the admission-time projector
        per_layer = eng._cross_embed(
            eng.params,
            jnp.zeros((eng.cross_seq_len, eng.cfg.dim), jnp.float32))
        jax.block_until_ready(per_layer)
        eng._cross_kv = eng._cross_write(
            eng._cross_kv, per_layer, jnp.int32(0))
        jax.block_until_ready(eng._cross_kv)
    # the host-side sampler used at admission time is part of the closed
    # set too — both signatures: scalar knobs (_admit_one, prefix path)
    # and per-row arrays at every warmed batch size (_admit_batch)
    V = eng.cfg.vocab_size
    eng._sample1(
        jnp.zeros((1, V), jnp.float32),
        jax.random.PRNGKey(0), 1.0, 0, 1.0).block_until_ready()
    for key in eng._prefill:
        if key[0] in ("cont", "rcont"):
            continue
        _, P_, K = key
        if P_ == 0:
            eng._sample1(
                jnp.zeros((K, V), jnp.float32), jax.random.PRNGKey(0),
                jnp.ones((K,), jnp.float32), jnp.zeros((K,), jnp.int32),
                jnp.ones((K,), jnp.float32)).block_until_ready()
