"""Metric publication — the autoscaling signal.

In the reference, ``cw_pub_metric`` pushes ``{APP}-counter``, ``{NODEPOOL}``
and ``{APP}-latency`` into CloudWatch namespace ``hw-agnostic-infer`` on every
served request, and KEDA scales deployments on ``SUM({app}-counter)``
(reference ``app/run-sd.py:22-37,166-173``, ``sd21-scaledobject.yaml:13-24``;
SURVEY.md §5 "metrics ARE the control plane").

TPU-native equivalent: the same three signals, published two ways at once —

- **Prometheus** (pull): a ``/metrics`` endpoint KEDA's prometheus trigger
  scrapes (``deploy/scale/*.yaml`` use
  ``sum(rate(shai_requests_total{app=...}))``).
- **JSON lines** (push, cloud-agnostic): one line per request on stdout that a
  log-router (CloudWatch EMF, GCP logging metric, fluentbit) turns into a
  counter — preserving the reference's push-model for clusters without a
  Prometheus stack.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

from .. import METRIC_NAMESPACE

try:  # gated: available in the serving image; optional in minimal envs
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Histogram,
        start_http_server,
    )

    _HAVE_PROM = True
except Exception:  # pragma: no cover
    _HAVE_PROM = False

_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 0.9, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: engine-phase histogram metric names on /metrics ← obs.steploop snapshot
#: keys (buckets live with the histograms: obs.steploop.TTFT_BUCKETS etc.)
ENGINE_HISTOGRAMS = {
    "ttft_seconds": ("shai_ttft_seconds",
                     "Time to first token (queue wait included)"),
    "tpot_seconds": ("shai_tpot_seconds",
                     "Per-output-token decode pace after the first token"),
    "queue_wait_seconds": ("shai_queue_wait_seconds",
                           "Submit-to-admission wait in the engine queue"),
    "step_gap_seconds": ("shai_engine_step_gap_seconds",
                         "Inter-step device gap: host time between a decode "
                         "readback and the next dispatch (0 when the async "
                         "pipeline dispatched ahead of the readback)"),
}
_ENGINE_GAUGES = {
    "running": ("shai_engine_running", "Sequences decoding right now"),
    "waiting": ("shai_engine_waiting", "Requests in the admission queue"),
    "chunking": ("shai_engine_chunking", "Slots mid chunked-prefill"),
    "kv_utilization": ("shai_engine_kv_utilization",
                       "KV page pool fraction held by LIVE sequences "
                       "(evictable prefix-cache blocks excluded — they "
                       "reclaim on demand)"),
    "kv_occupancy": ("shai_engine_kv_occupancy",
                     "KV page pool fraction allocated, cached blocks "
                     "included"),
    "kv_blocks_free": ("shai_engine_kv_blocks_free", "Free KV pool blocks"),
    "spec_acceptance_rate": ("shai_spec_acceptance_rate",
                             "Speculative draft acceptance rate"),
    "pad_fraction": ("shai_engine_pad_fraction",
                     "Fraction of dispatched token slots that were shape "
                     "padding (bucket windows past live tokens + batch pad "
                     "rows) — the waste the ragged kernel removes"),
}
_ENGINE_COUNTERS = {
    "steps": ("shai_engine_steps", "Engine steps executed"),
    "preemptions": ("shai_engine_preemptions",
                    "Recompute-preemptions (KV pool pressure)"),
    "recompiles": ("shai_engine_recompiles",
                   "Post-warm bucket-miss executable compiles"),
    "requests_finished": ("shai_engine_requests_finished",
                          "Requests finished by the engine"),
    "pipeline_flushes": ("shai_engine_pipeline_flushes",
                         "Async-decode lookahead steps retired early by a "
                         "composition/control-flow event"),
}
#: pad/real token counters export with a ``phase`` label (prefill /
#: chunk / decode / verify — where in a request's life the pad burned).
#: Any unphased remainder exports under phase="" so the labelled rows
#: always sum exactly to the engine's cumulative totals.
_PAD_PHASE_COUNTERS = {
    "pad_tokens": ("shai_engine_pad_tokens_total",
                   "Padded (wasted) token slots dispatched, cumulative",
                   "pad"),
    "real_tokens": ("shai_engine_real_tokens_total",
                    "Real context/prompt token slots dispatched, "
                    "cumulative",
                    "real"),
}
#: conformance-layer gauge families: each instrument riding the engine
#: telemetry object exports its flat numeric snapshot verbatim under a
#: prefix — obs.slo → shai_slo_* (per-objective burn rates + breach),
#: obs.hbm → shai_hbm_* (per-pool bytes, headroom, fragmentation, leak
#: flag), obs.sentinel → shai_perf_* (live/projected tok/s, conformance)
_CONFORMANCE_PREFIXES = (
    ("slo", "shai_slo_", "SLO burn-rate engine gauge"),
    ("hbm", "shai_hbm_", "Live HBM ledger gauge"),
    ("sentinel", "shai_perf_", "Perf-model sentinel gauge"),
)
#: host KV tier (kvtier.pool.HostKVTier snapshot keys → metric names):
#: counters carry the Prometheus _total suffix; gauges export raw
_KVTIER_COUNTERS = {
    "hits": ("shai_kvtier_hits_total",
             "Host KV tier: prefix blocks found resident"),
    "misses": ("shai_kvtier_misses_total",
               "Host KV tier: prefix walks that stopped short"),
    "evictions": ("shai_kvtier_evictions_total",
                  "Host KV tier: blocks LRU-evicted from the host pool"),
    "stores": ("shai_kvtier_stores_total",
               "Host KV tier: blocks demoted into the host pool"),
    "restored": ("shai_kvtier_restored_total",
                 "Host KV tier: blocks swapped back into the device pool"),
    "bytes": ("shai_kvtier_bytes_total",
              "Host KV tier: cumulative bytes copied into the host pool"),
    "errors": ("shai_kvtier_errors_total",
               "Host KV tier: failures degraded to recompute"),
    "dropped": ("shai_kvtier_dropped_total",
                "Host KV tier: demotions dropped (queue full / no capacity)"),
}
#: network KV transport (kvnet.client.KvNetStats snapshot keys): the
#: disaggregated-serving counters — fetched/served block flow, transport
#: bytes, and the degrade signal (fallbacks = fetches that fell back to
#: local recompute)
_KVNET_COUNTERS = {
    "fetched": ("shai_kvnet_fetched_total",
                "kvnet: KV blocks pulled from peer pods into the host "
                "tier"),
    "served": ("shai_kvnet_served_total",
               "kvnet: host-tier KV blocks served to peers over "
               "/kv/blocks"),
    "bytes": ("shai_kvnet_bytes_total",
              "kvnet: frame bytes moved through this pod's transport "
              "(served out + fetched in)"),
    "errors": ("shai_kvnet_errors_total",
               "kvnet: transport failures (connect/read/corrupt frames)"),
    "fallbacks": ("shai_kvnet_fallbacks_total",
                  "kvnet: fetches degraded to local recompute (open "
                  "breaker, transport failure, rejected frames)"),
}
#: live migration (kvnet.migrate.MigrateStats snapshot keys): the drain
#: ladder's counters — shipped/received/resumed move on the happy path,
#: failed counts ships that never landed, fallbacks counts ladder
#: degradations (no peer, refused restore)
_MIGRATE_COUNTERS = {
    "shipped": ("shai_migrate_shipped_total",
                "migrate: in-flight requests shipped to a peer at drain"),
    "received": ("shai_migrate_received_total",
                 "migrate: migration envelopes accepted from peers"),
    "resumed": ("shai_migrate_resumed_total",
                "migrate: migrated sequences re-admitted and completed "
                "on this pod"),
    "failed": ("shai_migrate_failed_total",
               "migrate: ship attempts that never landed on a peer"),
    "fallbacks": ("shai_migrate_fallbacks_total",
                  "migrate: ladder degradations (no peer, refused "
                  "restore, unencodable blocks) — each one recomputed "
                  "instead of failing"),
    "busy": ("shai_migrate_peer_busy_total",
             "migrate: 429 answers from saturated peers (inbox full or "
             "at SHAI_MIGRATE_MAX_INBOUND) — back-pressure the shipper "
             "routed around, never a failure"),
}
#: KV fabric (kvnet.directory.KvFabricStats snapshot keys): the fleet-
#: wide prefix-pool counters. Runbook: rising stale_holders = the
#: directory TTL outlives the pools (shorten SHAI_KVFABRIC_TTL_S);
#: rising remote_misses with flat stale_holders = holders unreachable —
#: under-replication (lower SHAI_KVFABRIC_HOT_N / add capacity)
_KVFABRIC_COUNTERS = {
    "probes": ("shai_kvfabric_probes_total",
               "KV fabric: peer-probe admissions attempted (the ladder's "
               "third rung)"),
    "remote_hits": ("shai_kvfabric_remote_hits_total",
                    "KV fabric: probes that landed a remote KV run"),
    "remote_misses": ("shai_kvfabric_remote_misses_total",
                      "KV fabric: probes that came up empty and "
                      "recomputed"),
    "replications": ("shai_kvfabric_replications_total",
                     "KV fabric: hot-prefix runs pulled by background "
                     "replication (/kv/pull)"),
    "directory_size": ("shai_kvfabric_directory_size_total",
                       "KV fabric: chain heads in this pod's local "
                       "directory"),
    "stale_holders": ("shai_kvfabric_stale_holders_total",
                      "KV fabric: holders that answered but no longer "
                      "held the advertised run"),
}
_KVTIER_GAUGES = {
    "used_bytes": ("shai_kvtier_used_bytes",
                   "Host KV tier: bytes resident in the host pool"),
    "capacity_bytes": ("shai_kvtier_capacity_bytes",
                       "Host KV tier: configured capacity "
                       "(SHAI_KVTIER_BYTES)"),
    "entries": ("shai_kvtier_entries", "Host KV tier: resident blocks"),
    "utilization": ("shai_kvtier_utilization",
                    "Host KV tier: used/capacity fraction"),
    "hit_rate": ("shai_kvtier_hit_rate",
                 "Host KV tier: hits / (hits + misses)"),
}
#: multi-tenant QoS: per-tenant attribution off the engine telemetry
#: (bounded label cardinality — obs.steploop.MAX_TENANT_LABELS tenants
#: plus "other"; the ledger-side gauges export from serve.app)
_TENANT_COUNTERS = {
    "requests": ("shai_tenant_requests_total",
                 "Requests submitted to the engine, per tenant"),
}
_TENANT_GAUGES = {
    "waiting": ("shai_tenant_waiting",
                "Engine queue depth held by this tenant (last step)"),
    "running": ("shai_tenant_running",
                "Decoding slots held by this tenant (last step)"),
}
_TENANT_TTFT = ("shai_tenant_ttft_seconds",
                "Time to first token per tenant (queue wait included) — "
                "the fairness number: a flooding tenant's queue must not "
                "move another tenant's TTFT")


class EngineTelemetryCollector:
    """Prometheus custom collector over an ``obs.steploop.StepTelemetry``.

    ``provider`` is a zero-arg callable returning the telemetry (or None
    before the engine loads) — resolved at scrape time, so registration can
    happen before ``service.load()`` built the engine.
    """

    def __init__(self, provider: Callable[[], Any], app: str):
        self.provider = provider
        self.app = app

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
            HistogramMetricFamily,
        )

        try:
            tele = self.provider()
        except Exception:
            return
        if tele is None:
            return
        snap = tele.snapshot()
        for key, (name, doc) in _ENGINE_GAUGES.items():
            if key in snap:
                g = GaugeMetricFamily(name, doc, labels=["app"])
                g.add_metric([self.app], float(snap[key]))
                yield g
        for key, (name, doc) in _ENGINE_COUNTERS.items():
            c = CounterMetricFamily(name, doc, labels=["app"])
            c.add_metric([self.app], float(snap.get(key, 0)))
            yield c
        phases = snap.get("pad_by_phase") or {}
        for key, (name, doc, col) in _PAD_PHASE_COUNTERS.items():
            c = CounterMetricFamily(name, doc, labels=["app", "phase"])
            total = float(snap.get(key, 0))
            phased = 0.0
            for phase in sorted(phases):
                v = float(phases[phase].get(col, 0))
                phased += v
                c.add_metric([self.app, phase], v)
            if total - phased or not phases:
                c.add_metric([self.app, ""], total - phased)
            yield c
        hists = tele.histograms()
        for key, (name, doc) in ENGINE_HISTOGRAMS.items():
            hs = hists.get(key)
            if hs is None:
                continue
            h = HistogramMetricFamily(name, doc, labels=["app"])
            h.add_metric(
                [self.app],
                [(str(le) if le != "+Inf" else "+Inf", float(c))
                 for le, c in hs["buckets"]],
                sum_value=float(hs["sum"]))
            yield h
        # conformance layer (PR 7): SLO burn rates, HBM ledger, perf
        # sentinel — attached to the telemetry object by the engine; a
        # tier without a given instrument simply exports nothing for it
        for attr, prefix, doc in _CONFORMANCE_PREFIXES:
            obj = getattr(tele, attr, None)
            if obj is None:
                continue
            try:
                snap = obj.snapshot()
            except Exception:
                continue
            for k, v in snap.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                g = GaugeMetricFamily(f"{prefix}{k}", doc, labels=["app"])
                g.add_metric([self.app], float(v))
                yield g
        # multi-tenant QoS: per-tenant request counts, queue/slot gauges,
        # and TTFT histograms — present only once a tenant tag (or QoS)
        # was seen, absent entirely on single-tenant pods
        tsnap = tele.tenant_snapshot() if hasattr(tele, "tenant_snapshot") \
            else {}
        if tsnap:
            for key, (name, doc) in _TENANT_COUNTERS.items():
                c = CounterMetricFamily(name, doc, labels=["app", "tenant"])
                for tenant, ent in sorted(tsnap.items()):
                    c.add_metric([self.app, tenant], float(ent.get(key, 0)))
                yield c
            for key, (name, doc) in _TENANT_GAUGES.items():
                g = GaugeMetricFamily(name, doc, labels=["app", "tenant"])
                for tenant, ent in sorted(tsnap.items()):
                    g.add_metric([self.app, tenant], float(ent.get(key, 0)))
                yield g
            h = HistogramMetricFamily(_TENANT_TTFT[0], _TENANT_TTFT[1],
                                      labels=["app", "tenant"])
            for tenant, hs in sorted(tele.tenant_histograms().items()):
                h.add_metric(
                    [self.app, tenant],
                    [(str(le) if le != "+Inf" else "+Inf", float(c))
                     for le, c in hs["buckets"]],
                    sum_value=float(hs["sum"]))
            yield h
        # network KV transport (kvnet): the disaggregated-serving counter
        # families, riding the same telemetry object — absent entirely on
        # pods outside the network KV plane
        kvn = getattr(tele, "kvnet", None)
        if kvn is not None:
            try:
                snap = kvn.snapshot()
            except Exception:
                snap = None
            if snap is not None:
                for key, (name, doc) in _KVNET_COUNTERS.items():
                    c = CounterMetricFamily(name, doc, labels=["app"])
                    c.add_metric([self.app], float(snap.get(key, 0)))
                    yield c
        # live migration (kvnet.migrate): the drain ladder's counter
        # families — attached by the engine, absent on engine-less pods
        mig = getattr(tele, "migrate", None)
        if mig is not None:
            try:
                snap = mig.snapshot()
            except Exception:
                snap = None
            if snap is not None:
                for key, (name, doc) in _MIGRATE_COUNTERS.items():
                    c = CounterMetricFamily(name, doc, labels=["app"])
                    c.add_metric([self.app], float(snap.get(key, 0)))
                    yield c
        # KV fabric (kvnet.directory): the fleet prefix-pool counters —
        # attached by the engine only when the fabric is armed, so a
        # fabric-off pod exports no shai_kvfabric_* family at all
        fab = getattr(tele, "kvfabric", None)
        if fab is not None:
            try:
                snap = fab.snapshot()
            except Exception:
                snap = None
            if snap is not None:
                for key, (name, doc) in _KVFABRIC_COUNTERS.items():
                    c = CounterMetricFamily(name, doc, labels=["app"])
                    c.add_metric([self.app], float(snap.get(key, 0)))
                    yield c
        # host KV tier (kvtier): counters with their _total contract +
        # occupancy gauges, from the same telemetry object
        kvt = getattr(tele, "kvtier", None)
        if kvt is not None:
            try:
                snap = kvt.snapshot()
            except Exception:
                return
            for key, (name, doc) in _KVTIER_COUNTERS.items():
                c = CounterMetricFamily(name, doc, labels=["app"])
                c.add_metric([self.app], float(snap.get(key, 0)))
                yield c
            for key, (name, doc) in _KVTIER_GAUGES.items():
                if key in snap:
                    g = GaugeMetricFamily(name, doc, labels=["app"])
                    g.add_metric([self.app], float(snap[key]))
                    yield g


#: request-reliability (resilience.idempotency): cache-counter key ->
#: exported family; the gauge rides separately below
_IDEMP_COUNTERS = {
    "replayed_total": ("shai_idemp_replayed_total",
                       "keyed duplicates answered from the completion "
                       "cache (no re-execution, no second charge)"),
    "joined_total": ("shai_idemp_joined_total",
                     "keyed duplicates that joined an in-flight "
                     "execution"),
    "misses_total": ("shai_idemp_misses_total",
                     "new idempotency keys (executions claimed)"),
    "evicted_total": ("shai_idemp_evicted_total",
                      "entries dropped by the bound or the TTL sweep"),
    "lookup_errors_total": ("shai_idemp_lookup_errors_total",
                            "lookups degraded to a miss (at-least-once "
                            "fallback)"),
}
_IDEMP_ENTRIES = ("shai_idemp_entries",
                  "live completion-cache entries (bounded by "
                  "SHAI_IDEMP_CACHE)")


class IdempotencyCollector:
    """Prometheus collector over ``resilience.idempotency``'s per-pod
    completion cache — same lazy-provider contract as
    :class:`EngineTelemetryCollector`."""

    def __init__(self, provider: Callable[[], Any], app: str):
        self.provider = provider
        self.app = app

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        try:
            cache = self.provider()
            snap = cache.snapshot() if cache is not None else None
        except Exception:
            return
        if snap is None:
            return
        for key, (name, doc) in _IDEMP_COUNTERS.items():
            c = CounterMetricFamily(name, doc, labels=["app"])
            c.add_metric([self.app], float(snap.get(key, 0)))
            yield c
        g = GaugeMetricFamily(_IDEMP_ENTRIES[0], _IDEMP_ENTRIES[1],
                              labels=["app"])
        g.add_metric([self.app], float(snap.get("entries", 0)))
        yield g


class MetricsPublisher:
    """Publishes the request counter + latency signals for one serving pod."""

    def __init__(
        self,
        app: str,
        nodepool: str,
        pod_name: str = "",
        emit_json: bool = True,
        registry: Optional["CollectorRegistry"] = None,
        stream=None,
    ):
        self.app = app
        self.nodepool = nodepool
        self.pod_name = pod_name
        self.emit_json = emit_json
        self._stream = stream or sys.stdout
        self._lock = threading.Lock()
        self._served = 0
        self.registry = None
        if _HAVE_PROM:
            self.registry = registry or CollectorRegistry()
            self._prom_requests = Counter(
                "shai_requests_total",
                "Served requests (the KEDA scaling signal)",
                ["app", "nodepool", "pod"],
                registry=self.registry,
            )
            self._prom_latency = Histogram(
                "shai_request_latency_seconds",
                "Per-request latency",
                ["app", "nodepool"],
                buckets=_LATENCY_BUCKETS,
                registry=self.registry,
            )
            # speculative decoding counters (drafted/accepted/committed):
            # the KEDA-visible signal pair behind acceptance rate — a tier
            # whose acceptance collapses decodes at vanilla pace and needs
            # MORE replicas per token served, so the autoscaler must see it
            self._prom_spec = {
                kind: Counter(
                    f"shai_spec_{kind}_total",
                    f"Speculative decoding: {kind} tokens",
                    ["app", "nodepool", "pod"],
                    registry=self.registry,
                )
                for kind in ("drafted", "accepted", "committed")
            }
            # load-shedding counter (resilience.admission): requests
            # refused at the door — per reason, so dashboards can split a
            # drain's 503s from an overload's 429s (runbook: README
            # "Resilience"; this is the pod-level twin of the failover
            # controller's overload trigger)
            self._prom_shed = Counter(
                "shai_shed_total",
                "Requests shed by the admission gate / drain",
                # tenant label (multi-tenant QoS): bounded upstream — the
                # serve layer passes ledger-sanitized tenant keys only, so
                # cardinality is capped at SHAI_QOS_MAX_TENANTS + "other"
                ["app", "nodepool", "reason", "tenant"],
                registry=self.registry,
            )
        self._spec_last = {"drafted": 0, "accepted": 0, "committed": 0}
        self._engine_last_steps = -1

    @property
    def served(self) -> int:
        with self._lock:
            return self._served

    def publish(self, latency_s: float, count: int = 1) -> None:
        """Record ``count`` served requests at ``latency_s`` seconds each."""
        with self._lock:
            self._served += count
        if _HAVE_PROM and self.registry is not None:
            self._prom_requests.labels(self.app, self.nodepool, self.pod_name).inc(count)
            self._prom_latency.labels(self.app, self.nodepool).observe(latency_s)
        if self.emit_json:
            # fixed metadata outside, the reference's three dynamically-named
            # CloudWatch metrics inside "data" (setdefault so a pathological
            # NODEPOOL equal to "{app}-counter" can't silently drop a signal)
            data = {f"{self.app}-counter": count}
            data.setdefault(self.nodepool, count)
            data[f"{self.app}-latency"] = round(latency_s, 4)
            line = json.dumps(
                {
                    "ns": METRIC_NAMESPACE,
                    "ts": round(time.time(), 3),
                    "pod": self.pod_name,
                    "data": data,
                }
            )
            print(line, file=self._stream, flush=True)

    def count_shed(self, reason: str, tenant: str = "") -> None:
        """Record one shed (refused) request under ``reason`` — exported as
        ``shai_shed_total{reason=...,tenant=...}`` and one JSON line for
        the push-model path (overloads are exactly when the control plane
        needs to see per-pod shed rates — and per-tenant shed rates are
        how a dashboard separates 'the pod is saturated' from 'one tenant
        is over budget'). ``tenant`` must arrive bounded (the serve layer
        passes the ledger's sanitized key); empty reads as ``default``."""
        if _HAVE_PROM and self.registry is not None:
            self._prom_shed.labels(self.app, self.nodepool, reason,
                                   tenant or "default").inc()
        if self.emit_json:
            # reason rides in the metric NAME: "data" is a name -> number
            # map for the CloudWatch-style consumer (a string value would
            # break its float() ingestion), mirroring the Prometheus twin's
            # reason label
            print(json.dumps({
                "ns": METRIC_NAMESPACE,
                "ts": round(time.time(), 3),
                "pod": self.pod_name,
                "data": {f"{self.app}-shed-{reason}": 1},
            }), file=self._stream, flush=True)

    def publish_spec(self, drafted: int, accepted: int,
                     committed: int) -> None:
        """Record CUMULATIVE speculative-decoding counters (the engine's
        ``SpecStats`` totals); Prometheus counters advance by the delta
        since the last call, and the JSON push path emits the cumulative
        snapshot plus the derived acceptance rate. Idempotent per snapshot —
        callers just forward the engine's current totals after each request.
        """
        # delta AND emission both under the lock: a concurrent publisher
        # finishing between them would print cumulative snapshots out of
        # order (totals going backwards on the push stream)
        with self._lock:
            cur = {"drafted": drafted, "accepted": accepted,
                   "committed": committed}
            delta = {k: max(0, cur[k] - self._spec_last[k]) for k in cur}
            self._spec_last = cur
            if not any(delta.values()):
                return
            if _HAVE_PROM and self.registry is not None:
                for kind, d in delta.items():
                    if d:
                        self._prom_spec[kind].labels(
                            self.app, self.nodepool, self.pod_name).inc(d)
            if self.emit_json:
                data = {f"{self.app}-spec-{k}": v for k, v in cur.items()}
                data[f"{self.app}-spec-acceptance"] = (
                    round(accepted / drafted, 4) if drafted else 0.0)
                print(json.dumps({
                    "ns": METRIC_NAMESPACE,
                    "ts": round(time.time(), 3),
                    "pod": self.pod_name,
                    "data": data,
                }), file=self._stream, flush=True)

    def attach_engine_telemetry(self, provider: Callable[[], Any]) -> bool:
        """Register the engine's step telemetry on this publisher's
        Prometheus registry (TTFT/TPOT/queue-wait histograms + step gauges
        and counters). ``provider`` resolves lazily at scrape time so the
        app factory can attach before the engine exists. Returns False when
        prometheus_client is unavailable (the JSON-line path —
        :meth:`publish_engine` — still works there)."""
        if not (_HAVE_PROM and self.registry is not None):
            return False
        self.registry.register(EngineTelemetryCollector(provider, self.app))
        return True

    def attach_idempotency(self, provider: Callable[[], Any]) -> bool:
        """Register the per-pod idempotency cache's counter families
        (``shai_idemp_*``) — the lazy-provider contract of
        :meth:`attach_engine_telemetry`."""
        if not (_HAVE_PROM and self.registry is not None):
            return False
        self.registry.register(IdempotencyCollector(provider, self.app))
        return True

    def publish_engine(self, tele: Any) -> None:
        """Emit one JSON line of engine step telemetry (the push-model twin
        of the Prometheus collector, for clusters scaling off a log
        router). Deduped on the step counter: a snapshot identical in step
        count to the last published one is dropped, so request bursts don't
        multiply identical lines. Accepts either a snapshot dict or the
        live telemetry object (``.steps`` / ``.snapshot()``); with the
        object form, deduped hot-path calls pay one int compare instead of
        building a snapshot that would be thrown away."""
        if not self.emit_json:
            return
        with self._lock:
            is_dict = isinstance(tele, dict)
            steps = tele.get("steps", 0) if is_dict else tele.steps
            if steps == self._engine_last_steps:
                return
            self._engine_last_steps = steps
            snapshot = tele if is_dict else tele.snapshot()
            data = {f"{self.app}-engine-{k}": v
                    for k, v in snapshot.items()
                    if isinstance(v, (int, float))}
            print(json.dumps({
                "ns": METRIC_NAMESPACE,
                "ts": round(time.time(), 3),
                "pod": self.pod_name,
                "data": data,
            }), file=self._stream, flush=True)

    def start_exporter(self, port: int) -> bool:
        """Start the Prometheus scrape endpoint; returns False if unavailable."""
        if not (_HAVE_PROM and self.registry is not None):
            return False
        start_http_server(port, registry=self.registry)
        return True
