"""The single serving runtime: one app factory for every model.

The reference copy-pastes ~200-line FastAPI servers per model
(``run-{sd,bert,vit,llama,yolo}.py``, ``*_model_api.py``; SURVEY.md §2.2).
Here the shared surface lives once, and a model contributes only a
:class:`ModelService` (load + warmup + infer + extra routes).

Uniform HTTP surface (reference parity, ``app/run-sd.py:148-203``):

- ``GET  /``                      self-describing config (redacted)
- ``GET  /health``                liveness
- ``GET  /readiness``             readiness — 503 until loaded + warm
- ``POST /benchmark``             ``{"n_runs": N}`` → percentile report
- ``GET  /load/{n}/infer/{m}``    benchmark + metric publication
- ``GET  /metrics``               Prometheus text (the KEDA signal)
- task routes from the service (``/genimage``, ``/generate``, ``/predict``…)

Model calls run on a single-worker executor so the event loop keeps serving
probes while a denoise loop holds the chip; device access is serialized,
matching one-model-per-pod semantics (one deployment unit == one model
replica, reference ``README.md:158-159``).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextvars
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import FlightRecorder
from ..obs import trace as obs_trace
from ..resilience import deadline as rz_deadline
from ..resilience import faults as rz_faults
from ..resilience import qos as rz_qos
from ..resilience.admission import AdmissionGate
from ..resilience.drain import DrainController
from ..utils.env import ServeConfig
from .asgi import App, HTTPError, Request, Response
from .latency import LatencyCollector, run_benchmark
from .metrics import MetricsPublisher

log = logging.getLogger(__name__)


class ModelService:
    """One model behind the uniform runtime. Subclasses implement the hooks.

    Lifecycle: ``load()`` (build params + jitted fns, pull artifacts) →
    ``warmup()`` (one synthetic inference per compiled shape, the readiness
    gate; reference ``app/run-sd.py:144-146``) → ``infer(payload)`` per
    request.
    """

    #: task name for the self-describing root endpoint
    task: str = "generic"
    #: route the default POST handler mounts at
    infer_route: str = "/infer"
    #: how many requests may be in ``infer`` simultaneously. 1 = the model
    #: call itself owns the device (default). Engine-backed services raise
    #: this to their slot count — infer() then only enqueues into the engine
    #: loop (which owns the device), so concurrent requests batch together.
    concurrency: int = 1
    #: multi-host serving contract (serve.multihost): True only when EVERY
    #: path to the device — warmup, infer, extra routes — goes through the
    #: methods named in ``mirror_methods``, so followers can mirror each
    #: call and join its collectives. A service with an unmirrored device
    #: entry would wedge the slice; serve_multihost refuses it.
    supports_multihost: bool = False
    mirror_methods: Tuple[str, ...] = ("infer",)

    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg

    def load(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def warmup(self) -> None:
        """One synthetic end-to-end inference; override for model specifics."""
        self.infer(self.example_payload())

    def example_payload(self) -> Dict[str, Any]:
        """Payload used by warmup and the benchmark endpoints."""
        return {}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError

    def extra_routes(self) -> List[Tuple[str, Tuple[str, ...], Callable]]:
        """Additional (pattern, methods, handler(request)) routes."""
        return []

    def ready_error(self) -> Optional[str]:
        """Post-warm liveness: non-None fails /readiness with the reason.

        Engine-backed services report a dead engine loop here so the LB
        drains the pod instead of routing into guaranteed 500s.
        """
        return None

    def liveness_error(self) -> Optional[str]:
        """Non-None fails ``/health`` (the LIVENESS probe) so Kubernetes
        restarts the pod. Reserved for wedged-beyond-recovery states only —
        engine-backed services report the step watchdog here (a stuck
        dispatch: work pending, no step completing). Readiness-grade
        trouble belongs in :meth:`ready_error`, which merely drains."""
        return None

    def drain(self, budget_s: float) -> None:
        """Finish in-flight work within ``budget_s`` seconds and stop
        accepting more (SIGTERM path). Engine-backed services drain their
        engine loop here; the default is a no-op (plain services have no
        queue beyond the in-flight requests the app already waits on)."""
        return None

    def extra_stats(self) -> Dict[str, float]:
        """Numeric service-level gauges, merged into ``/stats`` and exported
        as ``shai_service_<key>`` Prometheus gauges on ``/metrics`` (so the
        control plane can scale on queue depth or pool pressure, not just
        the request counter). Engine-backed services report queue/slot/block
        occupancy here."""
        return {}

    def affinity_digests(self) -> Optional[List[str]]:
        """Recently served prompt-affinity digests (``kvtier.affinity``),
        advertised under ``/stats`` → ``kvtier.affinity`` so the cova
        orchestrator can route a repeated prompt to the pod whose prefix
        cache / host tier is already warm. None = no advertisement
        (services without an engine or without prefix caching)."""
        return None

    #: disaggregated serving role (kvnet): advertised on ``/stats`` so
    #: cova can route prefill work to prefill pods and hand warm KV to
    #: decode pods; engine-backed services set it from
    #: ``kvnet.resolve_role`` (SHAI_ROLE / EngineConfig.role)
    role: str = "both"

    def kv_tier(self):
        """The host KV block pool (``kvtier.pool.HostKVTier``) backing the
        ``GET /kv/blocks`` transport endpoint, or None when this pod has
        no tier (the route then 404s — peers count a fallback and
        recompute)."""
        return None

    def kvnet_stats(self):
        """The pod's :class:`~..kvnet.client.KvNetStats` counters
        (``shai_kvnet_*``), shared by the serve side (``/kv/blocks``) and
        the fetch side (the decode-role handoff pull); None on pods
        without a tier — the families then never export."""
        return None

    # -- KV fabric (kvnet.directory) ---------------------------------------

    def affinity_heads(self) -> Optional[Dict[str, int]]:
        """Bounded affinity-digest -> chain-head map (``/stats`` →
        ``kvtier.aff_heads``): lets the text-only cova router resolve a
        prompt to the content-addressed head its fleet directory is
        keyed by. None = no fabric participation."""
        return None

    def fabric_pull(self, source: str, head: int) -> Optional[int]:
        """Background replication pull (``POST /kv/pull``): resolve the
        run's hashes via ``source``'s ``/kv/digests?head=`` and fetch it
        into the local tier — the hot-prefix replication path, reusing
        the migrate/warm-pull transport. Returns blocks landed, or None
        when this pod has no fabric (the route 404s and cova tries
        another under-warmed pod next cycle)."""
        return None

    # -- live migration (kvnet.migrate) ------------------------------------

    def wants_migration(self) -> bool:
        """True when the drain should run a migrate phase before the
        budget expires (engine-backed services with migration armed —
        ``SHAI_MIGRATE`` / a configured peer). Default False: plain
        services keep the legacy wait-then-stop drain exactly."""
        return False

    def migrate_inflight(self) -> int:
        """Ship every in-flight request that survived the drain's
        natural-completion window to a healthy peer (the engine snapshots
        each sequence; the waiters ship the manifests and return/stream
        ``migrated`` handoffs). Returns how many requests entered
        migration; 0 on services without an engine."""
        return 0

    def accept_migration(self, manifest, entries):
        """Accept one MIGRATE envelope (``POST /kv/migrate``): restore the
        KV run into the local tier and bank the manifest for its replay.
        Returns the ack dict, or None when this pod cannot accept
        migrations (the route then 404s and the shipper degrades to the
        cold-replay rung). Raises ``kvnet.migrate.MigrateBusy`` when the
        inbox is saturated (the route answers 429 + Retry-After and the
        shipper tries another peer)."""
        return None

    def migrate_busy(self):
        """Retry-After seconds when the migration inbox is saturated —
        the route 429s BEFORE reading the (potentially tens-of-MB)
        envelope body; None = accepting. Default None: services without
        an inbox never push back."""
        return None

    def pending_handoff(self) -> bool:
        """True while this pod still holds banked KV a peer may want to
        pull (``GET /kv/blocks``). The drain holds the server open —
        probe-class GET routes keep serving — until the budget expires
        while this is true: a prefill pod exiting the moment its own
        in-flight count hits zero would strand every handoff run its
        tier banked (the PR-15 drain bugfix)."""
        return False

    def spec_counters(self) -> Optional[Dict[str, int]]:
        """Cumulative speculative-decoding counters
        (``{"drafted", "accepted", "committed"}``) for
        :meth:`MetricsPublisher.publish_spec`, or None when the service has
        no speculative engine. The request path forwards these after each
        served inference so acceptance rate reaches the autoscaling plane."""
        return None

    def engine_telemetry(self):
        """The engine's ``obs.steploop.StepTelemetry`` (None for services
        without an engine). Resolved lazily — the app factory registers the
        Prometheus collector before ``load()`` built the engine — and read
        at every scrape, ``/stats`` call, and ``/debug/flight`` dump."""
        return None

    def step_records(self, n: int = 256) -> List[Dict[str, Any]]:
        """The last ``n`` engine step records for the flight recorder."""
        tele = self.engine_telemetry()
        return tele.recent_steps(n) if tele is not None else []

    def export_artifacts(self, artifact_root: str) -> int:
        """Export portable AOT artifacts (StableHLO via ``core.aot.AotCache``)
        under the artifact root; returns how many were written.

        ``compilectl`` calls this after warmup — the distributable analog of
        the reference pushing per-rank NEFFs to the hub
        (``app/compile-sd2.py:18-20``). Services that only rely on the
        persistent XLA cache return 0.
        """
        return 0


_SERVE_UI_HTML = """<!doctype html><meta charset="utf-8">
<title>%(app)s — %(task)s</title>
<style>body{font-family:sans-serif;max-width:52rem;margin:2rem auto}
textarea{width:100%%;font-family:monospace}pre{background:#f4f4f4;
padding:1rem;overflow:auto}img{max-width:100%%;margin-top:1rem}</style>
<h1>%(app)s <small>(%(task)s)</small></h1>
<p>POST payload for <code>%(route)s</code>:</p>
<textarea id=payload rows=6>%(example)s</textarea>
<p><button onclick="run()">run</button>
<a href="/stats">stats</a> · <a href="/metrics">metrics</a> ·
<a href="/">config</a></p>
<pre id=out></pre><div id=img></div>
<script>
async function run(){
  out.textContent = '...'; img.innerHTML = '';
  const r = await fetch('%(route)s',
    {method:'POST', body: payload.value});
  const body = await r.json();
  if (body.image_b64 && body.image_b64.length > 64) {
    img.innerHTML = '<img src="data:image/png;base64,' + body.image_b64 + '">';
    body.image_b64 = '(' + body.image_b64.length + ' b64 chars, shown below)';
  }
  out.textContent = JSON.stringify(body, null, 1);
}
</script>"""


def create_app(
    cfg: ServeConfig,
    service: ModelService,
    publisher: Optional[MetricsPublisher] = None,
) -> App:
    app = App(title=cfg.app)
    collector = LatencyCollector()
    pub = publisher or MetricsPublisher(cfg.app, cfg.nodepool, cfg.pod_name)
    state = {"loaded": False, "warm": False, "load_error": None,
             "inflight": 0, "lane_pending": 0}
    inflight_lock = threading.Lock()
    # request-lifecycle hardening (resilience): bounded admission in front
    # of the model lane + the SIGTERM drain flag. One threshold owner: the
    # gate prices saturation with the failover controller's numbers, so
    # pod-level 429s and fleet-level failover describe the same line.
    from ..orchestrate.capacity_checker import OverloadThresholds

    # multi-tenant QoS (resilience.qos): the tenant budget ledger rides
    # the admission gate — an over-budget tenant sheds with a Retry-After
    # derived from its token-bucket refill deficit while other tenants
    # keep serving; SHAI_TENANT_MAX_INFLIGHT optionally caps one tenant's
    # concurrency inside its budget
    from ..obs.util import env_int as _env_int

    # request reliability (resilience.idempotency): the bounded per-pod
    # completion cache keyed duplicates replay from. Consulted ONLY for
    # requests carrying X-SHAI-Idempotency-Key — keyless traffic never
    # touches it (the strict no-op gate), and non-idempotent replay stays
    # forbidden without a key (the PR-3 contract).
    from ..obs.util import env_float as _env_float
    from ..resilience import idempotency as rz_idemp

    idem = rz_idemp.IdempotencyCache(
        max_entries=_env_int("SHAI_IDEMP_CACHE", 1024),
        ttl_s=_env_float("SHAI_IDEMP_TTL_S", 600.0))

    ledger = rz_qos.TenantLedger.from_env()
    gate = AdmissionGate(
        OverloadThresholds(max_queue_depth=cfg.admit_max_queue,
                           max_kv_utilization=cfg.admit_max_kv),
        max_inflight=cfg.max_inflight,
        ledger=ledger,
        tenant_max_inflight=_env_int("SHAI_TENANT_MAX_INFLIGHT", 0))
    drainer = DrainController(budget_s=cfg.drain_budget_s)
    # flight recorder: every completed request's span timeline rings here
    # (the asgi layer closes each trace and sinks it), joined at dump time
    # by the engine's step records — GET /debug/flight
    flight = FlightRecorder()
    app.trace_sink = flight.record_request
    # engine telemetry → /metrics: TTFT/TPOT/queue-wait histograms + step
    # gauges/counters, resolved lazily at scrape time
    pub.attach_engine_telemetry(service.engine_telemetry)
    pub.attach_idempotency(lambda: idem)
    # the model lane: probes never queue behind it. Width 1 serializes device
    # access; engine-backed services widen it (their infer only enqueues).
    lane = concurrent.futures.ThreadPoolExecutor(
        max_workers=max(1, service.concurrency), thread_name_prefix="model")

    app.state.update(cfg=cfg, service=service, collector=collector, publisher=pub,
                     status=state, flight=flight, gate=gate, drainer=drainer,
                     ledger=ledger, idem=idem)
    # lifecycle probes and scrape surfaces must not ring the flight
    # recorder; /kv/blocks is probe-class too — a decode fleet pulling KV
    # runs would otherwise evict real request timelines from the ring
    app.trace_exclude |= {"/health/ready", "/debug/faults",
                          "/debug/conformance", "/profile", "/kv/blocks",
                          "/kv/migrate", "/kv/digests", "/kv/pull",
                          "/kv/protect", "/trace/{trace_id}"}

    def _do_load_and_warm():
        t0 = time.perf_counter()
        try:
            service.load()
            state["loaded"] = True
            log.info("%s: model loaded in %.1fs", cfg.app, time.perf_counter() - t0)
            if cfg.warmup:
                t1 = time.perf_counter()
                service.warmup()
                log.info("%s: warmup done in %.1fs", cfg.app, time.perf_counter() - t1)
            state["warm"] = True
        except Exception as e:
            # pod stays alive but never ready — the reference's fail-fast
            # startup self-test semantics (SURVEY.md §4.1) without a crash loop
            state["load_error"] = f"{type(e).__name__}: {e}"
            log.exception("%s: startup failed", cfg.app)

    @app.startup
    def _kick_off_load():
        # Loading runs on the model lane, NOT the event loop: the listen
        # socket binds immediately and /health + /readiness answer during the
        # multi-minute cold compile (/readiness returns 503 "loading").
        state["load_future"] = lane.submit(_do_load_and_warm)

    async def _run_model(fn: Callable, *args):
        loop = asyncio.get_running_loop()
        # run under a COPY of the caller's context: run_in_executor does not
        # propagate contextvars, and the request trace must follow the model
        # call onto the lane thread so spans opened there nest correctly
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(lane, lambda: ctx.run(fn, *args))

    def _require_ready():
        if state["load_error"]:
            raise HTTPError(500, f"model failed to load: {state['load_error']}")
        if not (state["loaded"] and state["warm"]):
            raise HTTPError(503, "model not ready")
        err = service.ready_error()
        if err:
            raise HTTPError(503, f"model unhealthy: {err}")

    # -- request lifecycle (resilience) ------------------------------------

    def _engine_snapshot() -> Optional[Dict[str, Any]]:
        try:
            tele = service.engine_telemetry()
            return None if tele is None else tele.snapshot()
        except Exception:
            return None

    def _inflight_counts() -> Tuple[int, int]:
        """One locked read of (inflight, lane_pending) — the lock is
        RELEASED before the gate/ledger run so the in-flight counters
        never nest with another lock (shai-race lock-order contract)."""
        with inflight_lock:
            return state["inflight"], state["lane_pending"]

    def _admit(tenant: str = ""):
        """Bounded admission: shed (429/503 + Retry-After) BEFORE the
        request parks a lane thread or enters the engine queue. ``tenant``
        is the ledger-bounded label — per-tenant budgets/caps shed here
        with a budget-derived Retry-After, and every shed is attributed
        per tenant on ``shai_shed_total``."""
        inflight, lane_pending = _inflight_counts()
        shed = gate.check(_engine_snapshot(), inflight=inflight,
                          draining=drainer.draining,
                          lane_width=max(1, service.concurrency),
                          lane_pending=lane_pending,
                          tenant=tenant)
        if shed is not None:
            pub.count_shed(shed.reason, tenant)
            raise HTTPError(shed.status, shed.detail, headers=shed.headers)

    def _deadline_of(request: Request) -> Optional[rz_deadline.Deadline]:
        """The request's deadline: header wins, DEADLINE_MS default fills
        in. Expired-on-arrival is a 504 before any model work."""
        try:
            dl = rz_deadline.deadline_from_headers(
                request.headers, default_ms=float(cfg.deadline_ms))
        except ValueError as e:
            raise HTTPError(400, str(e))
        if dl is not None and dl.expired:
            raise HTTPError(504, "deadline exceeded before processing")
        return dl

    class _InferScope:
        """Admission + deadline + QoS + in-flight accounting around one
        request. The deadline and the tenant/priority tag ride contextvars
        so ``_run_model``'s context copy carries them onto the lane thread
        (and into the engine loop)."""

        def __init__(self, request: Request):
            self.request = request
            self._token = None
            self._qos_token = None
            self._handed_off = False
            # resolved at __enter__: the ledger-bounded tenant label every
            # shed/charge/inflight count for this request attributes to
            self.tenant = ""

        def __enter__(self):
            raw_tenant, priority = rz_qos.qos_from_headers(
                self.request.headers)
            self.tenant = ledger.label_of(raw_tenant)
            _admit(self.tenant)
            dl = _deadline_of(self.request)
            self._token = rz_deadline.set_current_deadline(dl)
            # the engine tag carries the RAW (sanitized) tenant, not the
            # ledger's "default" label: an untagged request must reach
            # the engine untagged so a single-tenant pod keeps its
            # zero-cost FIFO path and exports no tenant families
            self._qos_token = rz_qos.set_current_qos(
                rz_qos.QosTag(tenant=raw_tenant, priority=priority))
            ledger.note_start(self.tenant)
            with inflight_lock:
                state["inflight"] += 1
                state["lane_pending"] += 1
            return dl

        def charge(self, out) -> None:
            """Debit the tenant's token budget with the request's actual
            usage: prompt + generated tokens for engine responses (OpenAI
            ``usage.total_tokens`` or the /generate fields), a floor of 1
            unit for token-less services/streams — so budgets degrade to
            request-rate metering where token counts don't exist."""
            tokens = 1
            if isinstance(out, dict):
                usage = out.get("usage")
                if isinstance(usage, dict) and isinstance(
                        usage.get("total_tokens"), (int, float)):
                    tokens = int(usage["total_tokens"])
                else:
                    try:
                        tokens = (int(out.get("n_tokens") or 0)
                                  + int(out.get("n_prompt") or 0))
                    except (TypeError, ValueError):
                        tokens = 1
            ledger.charge(self.tenant, max(1, tokens))

        def _dec_inflight(self):
            with inflight_lock:
                state["inflight"] -= 1

        def hand_off_inflight(self):
            """Streaming: the request is in flight until its stream DRAINS,
            not until the handler returns the StreamingResponse — defer the
            decrement to the returned callable (idempotent; called from the
            stream iterator's finally, which runs on drain, disconnect
            abort, and generator close alike). Keeps live SSE streams
            visible to MAX_INFLIGHT and the drain's in-flight wait. The
            lane-pending count drops NOW: the submission's lane thread is
            already free and the stream's engine work runs on the stream
            pool, so an open stream must not read as executor queue depth."""
            self._handed_off = True
            with inflight_lock:
                state["lane_pending"] -= 1
            released = {"v": False}
            tenant = self.tenant

            def release():
                if not released["v"]:
                    released["v"] = True
                    self._dec_inflight()
                    # stream drain/abort: the tenant's in-flight slot frees
                    # and its budget is debited the streaming floor (token
                    # counts never reach the app layer mid-SSE)
                    ledger.note_done(tenant)
                    ledger.charge(tenant, 1)

            return release

        def __exit__(self, *exc):
            if not self._handed_off:
                with inflight_lock:
                    state["inflight"] -= 1
                    state["lane_pending"] -= 1
                ledger.note_done(self.tenant)
            rz_deadline.reset_current_deadline(self._token)
            rz_qos.reset_current_qos(self._qos_token)
            return False

    def _begin_drain(on_done: Optional[Callable[[], None]] = None) -> bool:
        """SIGTERM semantics, callable without a signal (tests, /debug):
        flip readiness, shed new work, let in-flight requests finish up to
        the drain budget, drain the service (engine loop), then ``on_done``
        (the server's shutdown). Idempotent — one drain per process."""
        if not drainer.begin():
            return False
        log.warning("%s: draining (budget %.1fs) — readiness now 503",
                    cfg.app, drainer.budget_s)

        def _work():
            idle = lambda: _inflight_counts()[0] == 0  # noqa: E731
            # migrate phase (kvnet.migrate): give natural completion the
            # budget MINUS a reservation, then ship what's still running
            # to a healthy peer — pod death becomes a latency event for
            # the long tail instead of an error event at the deadline
            if service.wants_migration():
                from ..kvnet.migrate import migrate_reserve_s

                if not drainer.wait(idle, min_remaining=migrate_reserve_s(
                        drainer.budget_s)):
                    try:
                        n = service.migrate_inflight()
                        if n:
                            log.warning("%s: drain migrated %d in-flight "
                                        "request(s) to a peer", cfg.app, n)
                    except Exception:
                        log.exception("drain migrate phase failed — "
                                      "falling back to the budget wait")
            clean = drainer.wait(idle)
            if not clean:
                log.warning("%s: drain budget expired with %d requests "
                            "in flight", cfg.app, _inflight_counts()[0])
            try:
                service.drain(max(0.0, drainer.remaining_s))
            except Exception:
                log.exception("service drain failed")
            # prefill-handoff hold (the PR-15 drain bugfix): a pod whose
            # host tier still banks handoff KV keeps its probe-class GET
            # routes (/kv/blocks) serving until the budget expires, so
            # peers can pull the runs this pod warmed — exiting at
            # inflight==0 stranded them
            try:
                while service.pending_handoff() and drainer.remaining_s > 0:
                    time.sleep(0.05)
            except Exception:
                log.exception("pending-handoff hold failed")
            if on_done is not None:
                on_done()

        threading.Thread(target=_work, daemon=True, name="drain").start()
        return True

    app.state["begin_drain"] = _begin_drain

    # -- uniform surface ---------------------------------------------------
    @app.get("/")
    def root(request: Request):
        return {
            "app": cfg.app,
            "task": service.task,
            "model_id": cfg.model_id,
            "device": cfg.device,
            "endpoints": sorted({r.pattern for r in app.routes}),
            "config": cfg.describe(),
            "served": pub.served,
        }

    @app.get("/health")
    def health(request: Request):
        # LIVENESS: only wedged-beyond-recovery states fail it (the engine
        # step watchdog) — Kubernetes restarts the pod. A draining pod is
        # still live (it is finishing real work).
        err = service.liveness_error()
        if err:
            return Response({"status": "stuck", "error": err}, status=503)
        return {"status": "ok"}

    @app.get("/readiness")
    @app.get("/health/ready")
    def readiness(request: Request):
        if drainer.draining:
            # SIGTERM flips readiness first: the LB stops routing while
            # in-flight requests finish inside the drain budget
            return Response({"status": "draining"}, status=503)
        if state["load_error"]:
            return Response({"status": "failed", "error": state["load_error"]}, status=500)
        if not (state["loaded"] and state["warm"]):
            return Response({"status": "loading"}, status=503)
        err = service.ready_error()
        if err:
            return Response({"status": "unhealthy", "error": err}, status=503)
        return {"status": "ready"}

    async def _idem_replay_or_claim(key: str):
        """Consult the completion cache for a keyed request: a cached
        result (or a joined in-flight one) comes back as the response;
        None means this caller owns the execution. Joiners park on the
        entry's event OFF the event loop — the idempotency lock is HOT
        and the wait is unbounded-ish (the original's own deadline/600s
        backstop bounds it in practice)."""
        inj = rz_faults.get()
        await inj.asleep_at(rz_faults.IDEMP_LOOKUP)
        st, entry = idem.begin(key)
        if st == "new":
            return None
        if st == "done":
            return dict(entry.result, idempotent_replay=True)
        loop = asyncio.get_running_loop()
        woke = await loop.run_in_executor(None, entry.event.wait, 600.0)
        if entry.state == "done" and entry.result is not None:
            return dict(entry.result, idempotent_replay=True)
        if not woke:
            raise HTTPError(
                409, f"duplicate of an in-flight request (key {key!r}) "
                     f"that has not completed; retry later")
        # the original failed — failures are not cached, this duplicate
        # legitimately runs its own attempt
        return None

    @app.post(service.infer_route)
    async def task_infer(request: Request):
        _require_ready()
        # request reliability: keyed duplicates replay/join instead of
        # re-executing — BEFORE admission and _InferScope, so a replay
        # never charges the tenant ledger a second time
        key = request.headers.get(rz_idemp.IDEMP_HEADER, "")
        if key:
            if not rz_idemp.valid_key(key):
                raise HTTPError(400, "bad idempotency key (want "
                                     "[A-Za-z0-9_.:-]{1,128})")
            cached = await _idem_replay_or_claim(key)
            if cached is not None:
                return cached
        payload = request.json()
        if key:
            payload["idem_key"] = key
        t0 = time.perf_counter()
        try:
            scope = _InferScope(request)
            with scope:
                # annotation=False: this span is held across an await on the
                # event loop; the device-trace view comes from the engine's
                # own prefill/decode annotations on the lane thread
                with obs_trace.span("model_infer", annotation=False):
                    out = await _run_model(service.infer, payload)
            scope.charge(out)
        except BaseException:
            if key:
                idem.fail(key)
            raise
        dt = time.perf_counter() - t0
        collector.record(dt)
        pub.publish(dt)
        sc = service.spec_counters()
        if sc is not None:
            pub.publish_spec(**sc)
        tele = service.engine_telemetry()
        if tele is not None:
            pub.publish_engine(tele)
        if isinstance(out, dict):
            out.setdefault("latency_s", round(dt, 4))
        if key and isinstance(out, dict):
            # publish AFTER the latency stamp so a replay is byte-equal
            # to the original response (modulo the replay marker)
            idem.complete(key, out)
        return out

    @app.post("/benchmark")
    async def benchmark(request: Request):
        _require_ready()
        payload = request.json()
        n_runs = int(payload.get("n_runs", cfg.num_of_runs_inf))
        if n_runs < 1 or n_runs > 10_000:
            raise HTTPError(400, "n_runs must be in [1, 10000]")
        example = payload.get("payload") or service.example_payload()
        report = await _run_model(
            lambda: run_benchmark(lambda: service.infer(example), n_runs, collector)
        )
        return {"app": cfg.app, "report": report.to_dict()}

    @app.get("/load/{n_runs:int}/infer/{n_inf:int}")
    async def load_infer(request: Request, n_runs: int, n_inf: int):
        """Reference parity: N benchmark rounds of M inferences each, with
        metric publication per round (reference ``app/run-sd.py:157-175``)."""
        _require_ready()
        if n_runs < 1 or n_inf < 1 or n_runs * n_inf > 100_000:
            raise HTTPError(400, "bad load shape")
        example = service.example_payload()
        reports = []

        def _one_round():
            # per-sample publication keeps the request counter and the
            # latency histogram in lockstep (1 observation per inference)
            return run_benchmark(
                lambda: service.infer(example), n_inf, collector, on_sample=pub.publish
            )

        for _ in range(n_runs):
            rep = await _run_model(_one_round)
            reports.append(rep.to_dict())
        return {"app": cfg.app, "rounds": reports, "served_total": pub.served}

    @app.get("/metrics")
    def metrics(request: Request):
        if pub.registry is None:
            raise HTTPError(404, "prometheus_client not available")
        from prometheus_client import generate_latest

        return Response(generate_latest(pub.registry), media_type="text/plain; version=0.0.4")

    @app.get("/stats")
    def stats(request: Request):
        inflight, lane_pending = _inflight_counts()
        out = {
            "served": pub.served,
            "latency": collector.report(),
            "count": collector.count,
            "inflight": inflight,
            "lane_pending": lane_pending,
            "draining": drainer.draining,
        }
        if gate.shed_total:
            out["shed"] = {"total": gate.shed_total,
                           **gate.shed_by_reason()}
        # request reliability: the completion cache's counters — present
        # only once a keyed request touched it, so keyless pods keep
        # their exact pre-existing /stats shape
        isnap = idem.snapshot()
        if any(isnap.values()):
            out["idempotency"] = isnap
        try:
            svc = service.extra_stats()
        except Exception:
            svc = {}
        if svc:
            out["service"] = svc
        tele = service.engine_telemetry()
        if tele is not None:
            out["engine"] = tele.snapshot()
            # conformance sections (PR 7): the failover controller reads
            # "slo" (burn-rate breach → latency-driven failover trigger)
            # and cova /fleet aggregates "hbm"/"perf" per backend
            for sec, obj in (("slo", getattr(tele, "slo", None)),
                             ("hbm", getattr(tele, "hbm", None)),
                             ("perf", getattr(tele, "sentinel", None)),
                             ("kvtier", getattr(tele, "kvtier", None)),
                             ("migrate", getattr(tele, "migrate", None)),
                             ("kvfabric", getattr(tele, "kvfabric",
                                                  None))):
                if obj is not None:
                    try:
                        out[sec] = obj.snapshot()
                    except Exception:
                        pass
        # warm-prefix advertisement (kvtier.affinity): cova's prefix-
        # affinity router reads these digests off /fleet — exported even
        # tier-less, the DEVICE prefix cache is warm too
        aff = service.affinity_digests()
        if aff is not None:
            out.setdefault("kvtier", {})["affinity"] = aff
        # KV fabric (kvnet.directory): the host tier's bounded chain-head
        # advertisement plus the affinity-digest -> chain-head map — what
        # cova's fleet directory is built from. Both are O(bounded) reads
        # off incrementally maintained caches, never an entries walk.
        tier = service.kv_tier()
        if tier is not None and hasattr(tier, "advertisement"):
            out.setdefault("kvtier", {})["adverts"] = tier.advertisement()
        heads = service.affinity_heads()
        if heads:
            out.setdefault("kvtier", {})["aff_heads"] = heads
        # fleet autoscaler (PR 19): the controller's latest decision
        # snapshot — counters (shai_scaler_* families), per-pool state,
        # and the control contract it ran under — published through the
        # orchestrate.scaler module seam by an in-process controller
        # (cova-colocated or the sim harness); pods without one simply
        # omit the section
        try:
            from ..orchestrate.scaler import published as _scaler_pub

            sc = _scaler_pub()
            if sc:
                out["scaler"] = sc
        except Exception:
            pass
        # disaggregated serving (kvnet): the pod's role — what cova's
        # disagg router partitions the fleet by — plus the transport
        # counters when the pod participates in the network KV plane
        out["role"] = service.role
        kn = service.kvnet_stats()
        if kn is not None:
            try:
                out["kvnet"] = kn.snapshot()
            except Exception:
                pass
        # multi-tenant QoS: one "qos" section joining the budget ledger's
        # per-tenant usage (requests/tokens/inflight/shed/budget balance)
        # with the engine's per-tenant queue/slot/TTFT view and the
        # weighted-fair scheduler's pick counters — what cova /fleet
        # aggregates fleet-wide per tenant. Engine-side keys are
        # namespaced `engine_*`: the two sources count different things
        # ("requests" admitted at the door vs submitted to the engine —
        # they diverge on n>1 fan-outs) and run different cardinality
        # caps, so a silent same-key merge would clobber one truth with
        # the other
        tenants: Dict[str, Dict[str, Any]] = {}
        for t, ent in ledger.snapshot().items():
            tenants.setdefault(t, {}).update(ent)
        if tele is not None and hasattr(tele, "tenant_snapshot"):
            for t, ent in tele.tenant_snapshot().items():
                tenants.setdefault(t, {}).update(
                    {f"engine_{k}": v for k, v in ent.items()})
        sched = getattr(tele, "qos_sched", None) if tele is not None \
            else None
        if tenants or sched is not None or ledger.metered:
            out["qos"] = {"metered": ledger.metered, "tenants": tenants}
            if sched is not None:
                out["qos"]["scheduler"] = sched.snapshot()
        from ..core.aot import compile_stats

        out["aot"] = compile_stats()
        return out

    @app.get("/kv/blocks")
    async def kv_blocks(request: Request):
        """Network KV transport (kvnet): serve this pod's host-tier blocks
        by chain hash. ``?hashes=`` is a comma-joined list; the response
        is the LEADING contiguous resident run as length-prefixed binary
        frames (``kvnet.frames``) — ``(k, v)`` per block, or the quant
        4-tuple ``(k, v, ks, vs)``, byte-exact. Probe-class route: no
        admission gate (GET), excluded from the flight ring, bounded by
        ``MAX_BLOCKS_PER_REQUEST``; a pod without a tier 404s and the
        peer degrades to recompute. The copy-and-encode runs on the
        DEFAULT executor, not the event loop (a full-cap pull at real
        geometry is tens of MB of tobytes+crc — on the loop it would
        stall /health and /readiness) and not the model lane (a KV pull
        must never queue behind a denoise/decode holding the device)."""
        from ..kvnet import client as kvnet_client
        from ..kvnet import frames as kvnet_frames

        tier = service.kv_tier()
        if tier is None:
            raise HTTPError(404, "no host KV tier on this pod")
        raw = request.query.get("hashes", "")
        try:
            hashes = [int(h) for h in raw.split(",") if h.strip()]
        except ValueError:
            raise HTTPError(400, "hashes must be comma-joined integers")
        if not hashes:
            raise HTTPError(400, "missing hashes")
        if len(hashes) > kvnet_client.MAX_BLOCKS_PER_REQUEST:
            raise HTTPError(
                400, f"at most {kvnet_client.MAX_BLOCKS_PER_REQUEST} "
                     f"hashes per request")

        def _gather() -> Tuple[int, bytes]:
            run = tier.get_run(hashes)
            return len(run), kvnet_frames.encode_frames(run)

        n_run, body = await asyncio.get_running_loop().run_in_executor(
            None, _gather)
        stats = service.kvnet_stats()
        if stats is not None:
            stats.count_served(n_run, len(body))
        return Response(body, media_type="application/octet-stream",
                        headers={"x-shai-kv-blocks": str(n_run)})

    @app.get("/kv/digests")
    def kv_digests(request: Request):
        """KV fabric advertisement (kvnet.directory): this pod's bounded
        chain-head set — ``{"adverts": [{"head", "n", "seq"}, ...]}`` —
        or, with ``?head=``, one advertised run's full hash chain for a
        replication pull. Probe-class: O(bounded) reads off the tier's
        incrementally maintained caches (never an entries walk), served
        inline on the event loop, trace-excluded. A pod without a tier
        404s — a directory poller treats it as advertising nothing."""
        tier = service.kv_tier()
        if tier is None or not hasattr(tier, "advertisement"):
            raise HTTPError(404, "no host KV tier on this pod")
        raw = request.query.get("head", "")
        if raw:
            try:
                head = int(raw)
            except ValueError:
                raise HTTPError(400, "head must be an integer chain hash")
            return {"head": head, "hashes": tier.run_hashes(head)}
        return {"adverts": tier.advertisement()}

    @app.post("/kv/pull")
    async def kv_pull(request: Request):
        """Hot-prefix replication (kvnet.directory): cova asks this pod
        to pull one advertised run from ``source`` into its own tier —
        ``{"source": url, "head": chain_hash}``. Infrastructure route
        (no admission gate; the pull is background warmth, not a
        request), refused while draining, 404 on fabric-off pods so a
        misconfigured cova can never turn a cold pod into a puller. The
        blocking fetch runs on the default executor."""
        _require_ready()
        if drainer.draining:
            raise HTTPError(503, "pod is draining; pick another peer",
                            headers={"retry-after": "1"})
        body = request.json()
        try:
            source = str(body["source"])
            head = int(body["head"])
        except (ValueError, TypeError, KeyError):
            raise HTTPError(400, "need {source: url, head: chain_hash}")
        n = await asyncio.get_running_loop().run_in_executor(
            None, service.fabric_pull, source, head)
        if n is None:
            raise HTTPError(404, "no KV fabric on this pod")
        return {"fetched": int(n)}

    @app.post("/kv/protect")
    async def kv_protect(request: Request):
        """Last-holder eviction deferral (kvnet.directory): cova marks
        the runs this pod is the fleet's ONLY advertised holder of —
        ``{"heads": [chain_hash, ...], "ttl_s": s}`` — so LRU eviction
        skips them for one directory cycle and a probe in flight never
        races the fleet's last copy out of existence. Bounded, advisory
        (capacity still wins), 404 without a tier."""
        tier = service.kv_tier()
        if tier is None or not hasattr(tier, "protect"):
            raise HTTPError(404, "no host KV tier on this pod")
        body = request.json()
        try:
            heads = [int(h) for h in body.get("heads", [])]
            ttl_s = float(body.get("ttl_s", 5.0))
        except (ValueError, TypeError, AttributeError):
            raise HTTPError(400, "need {heads: [chain_hash], ttl_s: s}")
        return {"protected": tier.protect(heads, min(ttl_s, 60.0))}

    @app.post("/kv/migrate")
    async def kv_migrate(request: Request):
        """Live migration accept (kvnet.migrate): one MIGRATE envelope —
        manifest + CRC-checked block frames — restores into this pod's
        host tier and banks the manifest for its replay. Infrastructure
        route: no admission gate or tenant ledger (the request already
        paid admission on the dying pod; the resumed replay pays this
        pod's gate normally), trace-excluded, refused while draining (a
        dying pod must not accept hand-me-downs it would immediately
        re-ship). Decode + restore run on the default executor — an
        envelope is potentially tens of MB of frames and must not stall
        /health."""
        from ..kvnet import migrate as kv_migrate_mod
        from ..kvnet.client import MAX_BLOCKS_PER_REQUEST

        _require_ready()
        if drainer.draining:
            raise HTTPError(503, "pod is draining; pick another peer",
                            headers={"retry-after": "1"})
        # migrate-storm guard (cheap pre-body probe): a saturated inbox /
        # concurrent-inbound cap answers 429 so a simultaneous multi-pod
        # drain spreads over the other survivors — the shipper's
        # ship_any treats this as "try the next peer", never a failure
        busy_s = service.migrate_busy()
        if busy_s is not None:
            raise HTTPError(429, "migration inbox saturated; try "
                                 "another peer",
                            headers={"retry-after": f"{float(busy_s):g}"})
        body = request.body
        if not body:
            raise HTTPError(400, "empty migration envelope")
        # cheap size bound BEFORE any frame decode (the PR-14 fetch-side
        # lesson, applied to the accept side): an envelope larger than a
        # full legitimate ship — manifest cap + the served block cap at
        # this pod's block size — is refused without paying the decode
        # (which roughly doubles the allocation). Tier-less pods accept
        # manifest-only envelopes, so their bound is the manifest cap.
        tier = service.kv_tier()
        max_body = kv_migrate_mod.MAX_MANIFEST_BYTES + (1 << 16)
        if tier is not None:
            max_body += MAX_BLOCKS_PER_REQUEST * tier.block_nbytes * 2
        if len(body) > max_body:
            raise HTTPError(400, f"migration envelope of {len(body)} "
                                 f"bytes exceeds the {max_body}-byte cap")

        def _accept():
            manifest, entries = kv_migrate_mod.decode_migration(body)
            if len(entries) > MAX_BLOCKS_PER_REQUEST:
                raise kv_migrate_mod.MigrateError(
                    f"envelope carries {len(entries)} blocks, cap is "
                    f"{MAX_BLOCKS_PER_REQUEST}")
            return service.accept_migration(manifest, entries)

        try:
            ack = await asyncio.get_running_loop().run_in_executor(
                None, _accept)
        except kv_migrate_mod.MigrateError as e:
            raise HTTPError(400, f"bad migration envelope: {e}")
        except kv_migrate_mod.MigrateBusy as e:
            # check-then-accept race closed at the real accept gate: a
            # concurrent burst past the pre-body probe still 429s here
            raise HTTPError(429, "migration inbox saturated; try "
                                 "another peer",
                            headers={"retry-after":
                                     f"{e.retry_after_s:g}"})
        if ack is None:
            raise HTTPError(404, "this pod does not accept migrations")
        return ack

    @app.get("/debug/conformance")
    def debug_conformance(request: Request):
        """One-stop conformance verdict: declared budgets vs live reality.
        Joins the HBM ledger, SLO burn rates, and the perf sentinel into a
        single OK/attention payload — what a human curls FIRST on a
        degraded pod, before digging into /debug/flight."""
        tele = service.engine_telemetry()
        out: Dict[str, Any] = {"app": cfg.app}
        hbm = slo = perf = None
        if tele is not None:
            hbm = getattr(tele, "hbm", None)
            slo = getattr(tele, "slo", None)
            perf = getattr(tele, "sentinel", None)
            out["engine"] = tele.snapshot()
        out["hbm"] = hbm.snapshot() if hbm is not None else None
        out["slo"] = slo.snapshot() if slo is not None else None
        out["perf"] = perf.snapshot() if perf is not None else None
        verdict = {
            "hbm_leak_suspect": bool((out["hbm"] or {}).get("leak_suspect")),
            "slo_breach": bool((out["slo"] or {}).get("breach")),
            "perf_degraded": bool((out["perf"] or {}).get("degraded")),
        }
        verdict["ok"] = not any(verdict.values())
        out["verdict"] = verdict
        return out

    @app.get("/debug/faults")
    def debug_faults(request: Request):
        """The live fault-injection schedule (spec, seed, per-clause draw
        and firing counts) — how a chaos run confirms what actually fired."""
        return rz_faults.get().snapshot()

    @app.post("/debug/faults")
    def debug_faults_set(request: Request):
        """Replace the fault schedule at runtime: ``{"spec": "...", "seed"
        : 0}``. Armed only by the SHAI_FAULTS_ENDPOINT env opt-in — a
        production pod must not take fault writes off its serving port."""
        if not rz_faults.endpoint_enabled():
            raise HTTPError(403, "fault injection endpoint is not enabled "
                                 "(set SHAI_FAULTS_ENDPOINT=1)")
        body = request.json()
        try:
            inj = rz_faults.configure(str(body.get("spec", "")),
                                      int(body.get("seed", 0) or 0))
        except (TypeError, ValueError) as e:
            raise HTTPError(400, f"bad fault spec: {e}")
        return inj.snapshot()

    @app.get("/debug/flight")
    def debug_flight(request: Request):
        """Postmortem dump: the last-N completed request timelines (span
        trees, W3C trace ids) + the last-M engine step records. Bounded
        rings — safe to curl on a degraded pod at any time."""
        n_req = None
        if "requests" in request.query:
            try:
                n_req = max(0, int(request.query["requests"]))
            except ValueError:
                raise HTTPError(400, "requests must be an integer")
        return flight.dump(step_source=service.step_records,
                           n_requests=n_req)

    @app.get("/trace/{trace_id}")
    def trace_by_id(request: Request, trace_id: str):
        """This pod's shard of one distributed trace: every flight-ring
        record under ``trace_id`` (dict-indexed — no ring walk). 404 when
        the id never recorded here or has been evicted; cova's fleet
        ``/trace/{id}`` treats that as "no spans from this pod"."""
        traces = flight.traces_for(trace_id)
        if not traces:
            raise HTTPError(404, f"trace {trace_id} not in flight ring")
        return {"trace_id": trace_id, "traces": traces}

    if pub.registry is not None:
        # service gauges read at scrape time — queue depth / pool occupancy
        # become autoscaling signals alongside the request counter
        from prometheus_client.core import GaugeMetricFamily

        class _ServiceStatsCollector:
            def collect(self):
                try:
                    st = service.extra_stats()
                except Exception:
                    return
                for k, v in st.items():
                    if isinstance(v, (int, float)):
                        g = GaugeMetricFamily(
                            f"shai_service_{k}", f"service gauge {k}",
                            labels=["app"])
                        g.add_metric([cfg.app], float(v))
                        yield g

        pub.registry.register(_ServiceStatsCollector())

        from prometheus_client.core import CounterMetricFamily

        class _TenantLedgerCollector:
            """Per-tenant budget/usage gauges off the ledger (bounded
            cardinality by construction — the ledger collapses overflow
            tenants into "other"): the live balance is how a dashboard
            answers "why is this tenant seeing 429s" without log-diving."""

            def collect(self):
                try:
                    snap = ledger.snapshot()
                except Exception:
                    return
                if not snap:
                    return
                tok = CounterMetricFamily(
                    "shai_tenant_tokens_total",
                    "Tokens charged against the tenant budget "
                    "(prompt + generated; 1/request for token-less "
                    "services)", labels=["app", "tenant"])
                infl = GaugeMetricFamily(
                    "shai_tenant_inflight",
                    "Requests in flight per tenant", labels=["app", "tenant"])
                bal = GaugeMetricFamily(
                    "shai_tenant_budget_balance",
                    "Live token-bucket balance (negative = in debt, "
                    "admission refused until refill)",
                    labels=["app", "tenant"])
                for tenant, ent in sorted(snap.items()):
                    tok.add_metric([cfg.app, tenant],
                                   float(ent.get("tokens", 0)))
                    infl.add_metric([cfg.app, tenant],
                                    float(ent.get("inflight", 0)))
                    if "budget_balance" in ent:
                        bal.add_metric([cfg.app, tenant],
                                       float(ent["budget_balance"]))
                yield tok
                yield infl
                yield bal

        pub.registry.register(_TenantLedgerCollector())

    # one trace at a time; concurrent POSTs must not corrupt the session.
    # "task" pins the stop coroutine — the event loop holds tasks weakly,
    # and a GC'd stop task would leave the trace session open forever
    profile_state = {"until": 0.0, "dir": None, "task": None}

    @app.get("/profile")
    def profile_status(request: Request):
        """Profiler session state: clients used to have to probe with a
        POST and read the 409 to learn whether a trace was running. ``dir``
        is the LAST session's trace directory (current session's while one
        runs) so tooling can find the artifact without parsing logs."""
        now = time.time()
        running = now < profile_state["until"] or bool(profile_state["task"])
        return {
            "running": running,
            "seconds_left": round(max(0.0, profile_state["until"] - now), 1),
            "trace_dir": profile_state["dir"],
        }

    @app.post("/profile/{seconds:int}")
    async def profile(request: Request, seconds: int):
        """Capture a ``jax.profiler`` device trace for ``seconds`` while the
        pod keeps serving; the trace lands under the artifact root for
        xprof/tensorboard. SURVEY §5's tracing surface (the reference offers
        only neuron-top/nvitop via kubectl exec) — and the instrument behind
        the perf work (VERDICT r2 next-round #1/#9).
        """
        import os

        if seconds < 1 or seconds > 300:
            raise HTTPError(400, "seconds must be in [1, 300]")
        now = time.time()
        # still-running = countdown not elapsed OR the stop task hasn't
        # completed yet (on a loaded box the window can expire before the
        # event loop runs _stop_later — start_trace would then raise)
        if now < profile_state["until"] or profile_state.get("task"):
            raise HTTPError(409, f"trace already running "
                                 f"({max(0.0, profile_state['until'] - now):.0f}s left)")
        trace_dir = os.path.join(cfg.artifact_root, "traces", cfg.app,
                                 time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(trace_dir, exist_ok=True)
        import jax

        # arm the lockout only after the trace actually starts — a failed
        # start must not 409-block the endpoint with nothing running
        try:
            jax.profiler.start_trace(trace_dir)
        except RuntimeError as e:
            # profiler held by an out-of-band trace (e.g. a jax.profiler
            # user in-process): same client semantics as our own lockout
            raise HTTPError(409, f"trace already running: {e}")
        profile_state.update(until=now + seconds, dir=trace_dir)

        async def _stop_later():
            await asyncio.sleep(seconds)
            try:
                jax.profiler.stop_trace()
            except Exception:
                log.exception("profiler stop failed")
            finally:
                profile_state["until"] = 0.0
                profile_state["task"] = None

        profile_state["task"] = asyncio.get_running_loop().create_task(
            _stop_later())
        return {"trace_dir": trace_dir, "seconds": seconds,
                "hint": "inspect with: tensorboard --logdir <trace_dir>"}

    @app.get("/serve")
    def serve_ui(request: Request):
        """Interactive page on every model pod — the reference mounts Gradio
        at ``/serve`` on each server (``app/run-sd.py:203``); here it is a
        dependency-free HTML console over the same task route."""
        import json as _json

        example = _json.dumps(service.example_payload() or {"prompt": ""},
                              indent=1)
        html = _SERVE_UI_HTML % {
            "app": cfg.app, "task": service.task,
            "route": service.infer_route, "example": example,
        }
        return Response(html, media_type="text/html")

    # -- model-specific routes --------------------------------------------
    from .asgi import StreamingResponse

    for pattern, methods, handler in service.extra_routes():
        if tuple(methods) == ("GET",):
            # GET-only extra routes are metadata (e.g. /v1/models): no
            # admission gate, no deadline, no lane — an OpenAI SDK client
            # enumerating models must not eat a 429/503 from a pod that is
            # merely busy or draining, and a metadata probe must not
            # inflate the inflight gauge or shai_shed_total
            def _wrap_meta(h):
                async def _meta_handler(request: Request, **params):
                    _require_ready()
                    return h(request, **params)
                return _meta_handler
            app.route(pattern, tuple(methods))(_wrap_meta(handler))
            continue

        def _wrap(h):
            async def _handler(request: Request, **params):
                _require_ready()
                t0 = time.perf_counter()
                scope = _InferScope(request)
                with scope:
                    with obs_trace.span("model_infer", annotation=False):
                        out = await _run_model(lambda: h(request, **params))
                    if isinstance(out, StreamingResponse):
                        # the request stays in flight (and latency runs)
                        # until the stream DRAINS, not when the handler
                        # returns (that's just the submission) — so live
                        # SSE streams count against MAX_INFLIGHT and the
                        # drain actually waits for them
                        release = scope.hand_off_inflight()
                        inner = out.iterator

                        def timed_iter():
                            try:
                                for chunk in inner:
                                    yield chunk
                            finally:
                                release()
                                dt = time.perf_counter() - t0
                                collector.record(dt)
                                pub.publish(dt)

                        out.iterator = timed_iter()
                        return out
                scope.charge(out)
                dt = time.perf_counter() - t0
                collector.record(dt)
                pub.publish(dt)
                tele = service.engine_telemetry()
                if tele is not None:
                    pub.publish_engine(tele)
                return out
            return _handler
        app.route(pattern, tuple(methods))(_wrap(handler))

    return app


def serve_forever(cfg: ServeConfig, service: ModelService) -> None:
    """Pod entrypoint: build the app, start the metrics exporter, serve.

    Installs the SIGTERM graceful-drain path: readiness flips to 503 (the
    LB stops routing), new work sheds with Retry-After, in-flight requests
    finish inside ``cfg.drain_budget_s``, the engine loop drains, then the
    server stops and the process exits 0 — instead of Kubernetes' default
    SIGKILL killing mid-decode requests at the grace-period edge."""
    import signal

    from .httpd import Server

    pub = MetricsPublisher(cfg.app, cfg.nodepool, cfg.pod_name)
    app = create_app(cfg, service, publisher=pub)
    server = Server(app, port=cfg.port)

    def _on_sigterm(signum, frame):
        app.state["begin_drain"](on_done=server.request_shutdown)

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (embedded/test use)
        log.warning("cannot install SIGTERM drain handler off the main "
                    "thread; relying on the platform grace period")
    server.run()
