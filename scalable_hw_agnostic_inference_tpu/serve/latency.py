"""Latency percentile collection and benchmark reports.

Single implementation of what the reference duplicates verbatim in eight
servers (``LatencyCollector`` + ``benchmark()``, reference
``app/run-sd.py:49-102``, ``app/vllm_model_api.py:61-109``, ...; see
SURVEY.md §2.2). The report shape — p0/p50/p90/p95/p99/p100 plus throughput —
is kept so dashboards built against the reference read identically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

PERCENTILES = (0, 50, 90, 95, 99, 100)


class LatencyCollector:
    """Thread-safe reservoir of request latencies with percentile readout."""

    def __init__(self, max_samples: int = 100_000):
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self._max_samples = max_samples
        self._total = 0

    def record(self, latency_s: float) -> None:
        with self._lock:
            self._total += 1
            if len(self._samples) < self._max_samples:
                self._samples.append(latency_s)
            else:
                # reservoir-style overwrite keeps memory bounded under load
                self._samples[self._total % self._max_samples] = latency_s

    def timed(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` and record its wall time; returns ``fn``'s result."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        self.record(time.perf_counter() - t0)
        return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    @staticmethod
    def _interp(data: List[float], p: float) -> float:
        if not data:
            return 0.0
        if p <= 0:
            return data[0]
        if p >= 100:
            return data[-1]
        # linear interpolation between closest ranks
        rank = (p / 100.0) * (len(data) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(data) - 1)
        frac = rank - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def percentile(self, p: float) -> float:
        with self._lock:
            data = sorted(self._samples)
        return self._interp(data, p)

    def report(self) -> Dict[str, float]:
        # one locked snapshot + one sort, so percentiles within a report are
        # mutually consistent under concurrent record()s
        with self._lock:
            data = sorted(self._samples)
        return {f"p{p}": self._interp(data, p) for p in PERCENTILES}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._total = 0


@dataclass
class BenchmarkReport:
    """Result of ``run_benchmark``: percentiles + throughput."""

    n_runs: int
    total_s: float
    latency_percentiles: Dict[str, float]
    throughput_rps: float
    extra: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict:
        d = {
            "n_runs": self.n_runs,
            "total_time_s": round(self.total_s, 4),
            "throughput_rps": round(self.throughput_rps, 4),
        }
        d.update({k: round(v, 4) for k, v in self.latency_percentiles.items()})
        d.update(self.extra)
        return d


def run_benchmark(
    fn: Callable[[], object],
    n_runs: int,
    collector: Optional[LatencyCollector] = None,
    on_sample: Optional[Callable[[float], None]] = None,
) -> BenchmarkReport:
    """Call ``fn`` ``n_runs`` times, measuring per-call latency.

    ``on_sample`` receives each individual latency (the serving runtime feeds
    the metrics publisher with it so counters and histograms stay in lockstep).
    The serving runtime exposes this via ``POST /benchmark`` and
    ``GET /load/{n}/infer/{m}``, matching the reference's built-in
    measurement instrument (reference ``app/run-sd.py:157-175``).
    """
    local = LatencyCollector()
    t0 = time.perf_counter()
    for _ in range(n_runs):
        t1 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t1
        local.record(dt)
        if collector is not None:
            collector.record(dt)
        if on_sample is not None:
            on_sample(dt)
    total = time.perf_counter() - t0
    return BenchmarkReport(
        n_runs=n_runs,
        total_s=total,
        latency_percentiles=local.report(),
        throughput_rps=(n_runs / total) if total > 0 else 0.0,
    )
