"""Latency percentile collection and benchmark reports.

Single implementation of what the reference duplicates verbatim in eight
servers (``LatencyCollector`` + ``benchmark()``, reference
``app/run-sd.py:49-102``, ``app/vllm_model_api.py:61-109``, ...; see
SURVEY.md §2.2). The report shape — p0/p50/p90/p95/p99/p100 plus throughput —
is kept so dashboards built against the reference read identically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..utils.latency import LatencyCollector, PERCENTILES  # noqa: F401

@dataclass
class BenchmarkReport:
    """Result of ``run_benchmark``: percentiles + throughput."""

    n_runs: int
    total_s: float
    latency_percentiles: Dict[str, float]
    throughput_rps: float
    extra: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict:
        d = {
            "n_runs": self.n_runs,
            "total_time_s": round(self.total_s, 4),
            "throughput_rps": round(self.throughput_rps, 4),
        }
        d.update({k: round(v, 4) for k, v in self.latency_percentiles.items()})
        d.update(self.extra)
        return d


def run_benchmark(
    fn: Callable[[], object],
    n_runs: int,
    collector: Optional[LatencyCollector] = None,
    on_sample: Optional[Callable[[float], None]] = None,
) -> BenchmarkReport:
    """Call ``fn`` ``n_runs`` times, measuring per-call latency.

    ``on_sample`` receives each individual latency (the serving runtime feeds
    the metrics publisher with it so counters and histograms stay in lockstep).
    The serving runtime exposes this via ``POST /benchmark`` and
    ``GET /load/{n}/infer/{m}``, matching the reference's built-in
    measurement instrument (reference ``app/run-sd.py:157-175``).
    """
    local = LatencyCollector()
    t0 = time.perf_counter()
    for _ in range(n_runs):
        t1 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t1
        local.record(dt)
        if collector is not None:
            collector.record(dt)
        if on_sample is not None:
            on_sample(dt)
    total = time.perf_counter() - t0
    return BenchmarkReport(
        n_runs=n_runs,
        total_s=total,
        latency_percentiles=local.report(),
        throughput_rps=(n_runs / total) if total > 0 else 0.0,
    )
