"""Shared per-unit helpers: tokenizer resolution, payload decoding, SSE
detokenization.

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility.
"""

from __future__ import annotations

import base64
import io
import logging
from typing import Any, Dict, Optional

import numpy as np

from ..asgi import HTTPError

log = logging.getLogger(__name__)


class HashTokenizer:
    """Deterministic offline tokenizer (tiny tier): hash words into ids."""

    def __init__(self, vocab_size: int, max_len: int):
        self.vocab_size = vocab_size
        self.max_len = max_len

    def __call__(self, text: str):
        import hashlib

        ids = [1]  # [CLS]-ish
        for w in text.lower().split()[: self.max_len - 2]:
            h = int(hashlib.md5(w.encode()).hexdigest(), 16)
            ids.append(2 + h % (self.vocab_size - 3))
        ids.append(self.vocab_size - 1)  # [SEP]/eot — also the argmax id
        mask = [1] * len(ids) + [0] * (self.max_len - len(ids))
        ids = ids + [0] * (self.max_len - len(ids))
        return np.array(ids), np.array(mask)


class SseTextAssembler:
    """Incremental detokenization for SSE token streams.

    Three properties the naive decode-everything loop lacks:

    - **bounded re-decode**: only the held (unflushed) token window is
      re-decoded per token, compacting at whitespace boundaries — O(n·W),
      not O(n²), and lock hold time stays constant;
    - **stop sequences never leak**: text ending with a proper prefix of a
      stop string is held back until the next token disambiguates, so a stop
      spanning a token boundary is truncated exactly like the non-streaming
      path;
    - **partial-UTF-8 holdback with end flush**: trailing U+FFFD is held (it
      may be half a multi-byte sequence) but ``finish()`` flushes it, since
      a model can legitimately end on undecodable bytes.
    """

    # forced compaction bound: newline boundaries are the safe reset points
    # (a mid-sequence suffix re-decode can drop a sentencepiece leading
    # space), so only force a reset once the window grows well past any
    # reasonable line length
    COMPACT_AT = 128

    def __init__(self, decode_fn, stops=()):
        self.decode = decode_fn
        self.stops = [s for s in stops if s]
        self.held: list = []
        self.sent = 0          # chars of the held window already emitted
        self.stopped = False

    def _holdback(self, h: str) -> int:
        """Chars at the end of ``h`` that must not be emitted yet."""
        safe = len(h)
        while safe > 0 and h[safe - 1] == "�":
            safe -= 1
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, safe), 0, -1):
                if h[:safe].endswith(s[:k]):
                    hold = max(hold, k)
                    break
        return safe - hold

    def push(self, tok: int) -> str:
        """Feed one token; return the text delta now safe to emit."""
        if self.stopped:
            return ""
        self.held.append(int(tok))
        h = self.decode(self.held)
        for s in self.stops:
            cut = h.find(s)
            if cut >= 0:
                self.stopped = True
                delta = h[self.sent:cut] if cut > self.sent else ""
                self.sent = len(h)
                return delta
        safe = self._holdback(h)
        delta = h[self.sent:safe] if safe > self.sent else ""
        self.sent = safe
        if self.sent == len(h) and h:
            if h.endswith("\n"):
                self.held = []
                self.sent = 0
            elif len(self.held) >= self.COMPACT_AT:
                # forced mid-line compaction keeps ONE overlap token: the
                # next window then decodes with a preceding-token context,
                # so sentencepiece leading-space normalization cannot drop
                # a space at the seam (ADVICE r3). sent re-anchors to the
                # overlap token's solo decode — the new window's coordinate
                # system.
                self.held = self.held[-1:]
                self.sent = len(self.decode(self.held))
        return delta

    def finish(self) -> str:
        """End of stream: flush anything the holdbacks retained."""
        if self.stopped or not self.held:
            return ""
        h = self.decode(self.held)
        delta = h[self.sent:]
        self.sent = len(h)
        return delta


def _hf_tokenizer(model_id: str, token: str = "", cache: str = ""):
    """Load an HF tokenizer, optionally backed by an artifact-local copy.

    ``cache`` names a directory under the weight artifact (the reference's
    COMPILED_MODEL_ID pull carries tokenizer files alongside the NEFFs, so a
    hub-less pod still boots). First hub fetch persists the files there; a
    later boot with the artifacts PVC but no hub access restores from it.
    """
    import os
    import shutil

    from transformers import AutoTokenizer

    cached_bad = False
    if cache and os.path.isdir(cache):
        try:
            return AutoTokenizer.from_pretrained(cache)
        except Exception:
            # do NOT delete here: the read failure may be transient and the
            # cache dir is shared across pods on the artifacts PVC —
            # destroy a (possibly torn) copy only with a good one in hand
            log.exception("tokenizer artifact unreadable — refetching")
            cached_bad = True
    tok = AutoTokenizer.from_pretrained(model_id, token=token or None)
    if cache:
        tmp = f"{cache}.{os.getpid()}.tmp"
        try:
            tok.save_pretrained(tmp)
            if cached_bad:
                shutil.rmtree(cache, ignore_errors=True)
            # atomic when cache doesn't exist; if a concurrent pod won the
            # race the rename fails and we just keep their copy
            os.rename(tmp, cache)
        except Exception:
            log.exception("tokenizer artifact save failed (serving anyway)")
            shutil.rmtree(tmp, ignore_errors=True)
    return tok


IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def tokenize_to_length(tok, text: str, length: int) -> np.ndarray:
    """Fixed-length [1, length] int32 ids from a HashTokenizer or HF fast
    tokenizer — one helper for every fixed-shape conditioning path."""
    if isinstance(tok, HashTokenizer):
        ids, _ = tok(text)
        return np.asarray(ids)[None, :length].astype(np.int32)
    enc = tok(text, padding="max_length", truncation=True, max_length=length)
    return np.asarray(enc["input_ids"], np.int32)[None]


def decode_image(payload: Dict[str, Any], size, width: Optional[int] = None,
                 mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)) -> np.ndarray:
    """base64 PNG/JPEG (or 'random') → normalized NHWC float array.

    ``size`` is the height (and width when ``width`` is omitted). Default
    normalization is HF ViT/CLIP's 0.5/0.5; detection models pass ImageNet
    statistics.
    """
    h = size
    w = width if width is not None else size
    b64 = payload.get("image_b64", "")
    if not b64 or b64 == "random":
        rng = np.random.default_rng(0)
        return rng.standard_normal((1, h, w, 3)).astype(np.float32)
    from PIL import Image

    img = Image.open(io.BytesIO(base64.b64decode(b64))).convert("RGB")
    img = img.resize((w, h))
    arr = np.asarray(img, dtype=np.float32) / 255.0
    arr = (arr - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    return arr[None]


