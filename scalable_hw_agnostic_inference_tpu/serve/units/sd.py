"""Stable Diffusion txt2img unit (reference run-sd.py / run-sd2.py).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
from .common import HashTokenizer, _hf_tokenizer, tokenize_to_length

log = logging.getLogger(__name__)


class SDService(ModelService):
    """Text-to-image — parity with reference ``run-sd.py``/``run-sd2.py``
    (SD2.1 512x512, DDIM swap at ``app/run-sd.py:108``, base64 PNG response
    ``:177-181``). The whole denoise loop is one jitted scan
    (``models.sd.StableDiffusion``); warmup compiles the serving shape so
    readiness implies the executable is built.
    """

    task = "text-to-image"
    infer_route = "/genimage"

    def __init__(self, cfg: ServeConfig):
        super().__init__(cfg)
        # request coalescing (SD_BATCH_MAX > 1): concurrent /genimage
        # requests sharing (steps, guidance) run as ONE batched denoise —
        # the diffusion analogue of the engine's batched prefill admission.
        # The lane widens to the batch cap so followers can sit in the
        # coalescer while a leader drives the device.
        import threading

        # clamp to a power of two: warmup compiles exactly the pow2 bucket
        # ladder, and _run_batch rounds up — a non-pow2 cap would let a
        # request land in a bucket no warmup built (post-ready compile)
        raw = max(1, int(cfg.sd_batch_max))
        self._batch_max = 1 << (raw.bit_length() - 1)
        if self._batch_max != raw:
            log.warning("SD_BATCH_MAX=%d clamped to %d (pow2 buckets)",
                        raw, self._batch_max)
        self.concurrency = self._batch_max
        self._pend_lock = threading.Lock()
        self._pending: list = []   # (key, item, Future)
        self._tok_lock = threading.Lock()  # HF tokenizers aren't thread-safe
        self._coalesce_window_s = 0.02     # ~2% of a 1 s denoise
        # coalescing observability: /stats + Prometheus gauges (scaling and
        # breaking-point analysis read batch occupancy, not just RPS)
        self._n_batches = 0
        self._n_coalesced = 0
        from collections import deque

        # recent batch sizes: the CURRENT-utilization signal (a lifetime
        # mean converges and stops responding to overload)
        self._recent_batches: deque = deque(maxlen=32)

    def load(self) -> None:
        from ...models import clip, sd

        cfg = self.cfg
        if cfg.model_id in ("", "tiny"):
            variant = sd.SDVariant.tiny()
            ccfg = clip.ClipTextConfig.tiny()
            text_model = clip.ClipTextEncoder(ccfg)
            text_params = text_model.init(
                jax.random.PRNGKey(cfg.seed), jnp.zeros((1, 8), jnp.int32)
            )
            unet = sd.UNet2DCondition(variant.unet)
            unet_params = unet.init(
                jax.random.PRNGKey(cfg.seed + 1),
                jnp.zeros((1, 8, 8, variant.unet.in_channels)),
                jnp.zeros((1,), jnp.int32),
                jnp.zeros((1, 8, variant.unet.cross_attention_dim)),
            )
            vae = sd.AutoencoderKL(variant.vae)
            vae_params = vae.init(
                jax.random.PRNGKey(cfg.seed + 2),
                jnp.zeros((1, 8, 8, variant.vae.latent_channels)),
            )
            self.tokenizer = HashTokenizer(ccfg.vocab_size, ccfg.max_position)
            self.seq_len = ccfg.max_position
        else:
            from transformers import CLIPTextModel

            from ...models import unet as unet_mod
            from ...models import vae as vae_mod

            root = sd.resolve_checkpoint_dir(cfg.model_id, cfg.hf_token)
            variant = sd.variant_from_checkpoint(root)
            tm = CLIPTextModel.from_pretrained(root, subfolder="text_encoder")
            ccfg = clip.ClipTextConfig.from_hf(tm.config)
            text_model = clip.ClipTextEncoder(ccfg)
            text_params = clip.params_from_torch(tm, ccfg)
            del tm
            unet_params = unet_mod.params_from_torch(
                sd.load_torch_state(f"{root}/unet"), variant.unet
            )
            vae_params = vae_mod.params_from_torch(
                sd.load_torch_state(f"{root}/vae"), variant.vae
            )
            self.tokenizer = _hf_tokenizer(root + "/tokenizer", cfg.hf_token)
            self.seq_len = ccfg.max_position
            # UNet params in bf16 (pure hot path); VAE params stay fp32 but
            # its compute runs bf16 via the module dtype (models.vae)
            from ...models.convert import cast_f32_to_bf16

            unet_params = cast_f32_to_bf16(unet_params)

        text_params = jax.device_put(text_params)
        text_fn = jax.jit(lambda ids: text_model.apply(text_params, ids)[0])
        self.pipe = sd.StableDiffusion(
            variant,
            jax.device_put(unet_params),
            jax.device_put(vae_params),
            text_fn,
            scheduler=cfg.scheduler,
        )
        self.variant = variant
        if cfg.model_id in ("", "tiny"):
            self.height = self.width = variant.default_size
        else:
            self.height, self.width = cfg.height, cfg.width
        # XLA compiles one executable per steps value — a client must not be
        # able to force arbitrary compiles, so steps is a closed set (env
        # STEPS_BUCKETS opts extra values in; all are compile-warmed below)
        self.steps_allowed = {cfg.num_inference_steps}
        if cfg.steps_buckets:
            self.steps_allowed |= {
                int(s) for s in cfg.steps_buckets.split(",") if s.strip()
            }
        # boot from exported StableHLO artifacts when the compile Job left
        # them in the artifact root (core.aot.AotCache) — the reference's
        # pull-compiled-NEFFs-from-hub boot (sd21-inf2-deploy.yaml:60-61)
        import os

        self.aot_loaded = 0
        aot_dir = os.path.join(cfg.artifact_root, "aot")
        if os.path.isdir(aot_dir):
            from ...core.aot import AotCache

            cache = AotCache(aot_dir)
            by_name = {m["name"]: k for k, m in cache.keys().items()}
            # install artifacts under the keys serving TRAFFIC actually
            # hits: the latents-as-argument ('batch', b, ...) executables in
            # coalescing mode, the in-graph single path otherwise — a
            # single-path artifact on a coalescing unit would load but
            # never serve a request (dead weight masquerading as coverage)
            for steps in sorted(self.steps_allowed):
                for shape_key, name in self._aot_keys(steps):
                    key = by_name.get(name)
                    if not key:
                        continue
                    try:
                        fn = cache.load(key)
                    except Exception as e:  # platform mismatch, stale
                        log.warning("AOT artifact %s unusable (%s); jit "
                                    "instead", key, e)
                        continue
                    self.pipe._denoise_cache[shape_key] = fn
                    self.aot_loaded += 1
            if self.aot_loaded:
                log.info("sd: %d pipeline executable(s) from AOT artifacts",
                         self.aot_loaded)

    def _aot_keys(self, steps: int):
        """(denoise-cache key, artifact name) pairs for one steps value —
        the single source of truth shared by export (compile Job) and boot
        load, so the executables exported are exactly the ones served."""
        f = self.pipe.vae_scale
        h, w = self.height // f, self.width // f
        stem = f"sd-{self.variant.name}-{self.height}x{self.width}-s{steps}"
        if self._batch_max == 1:
            return [((1, h, w, steps), stem)]
        pairs = []
        b = 1
        while b <= self._batch_max:
            pairs.append((("batch", b, h, w, steps), f"{stem}-b{b}"))
            b *= 2
        return pairs

    def export_artifacts(self, artifact_root: str) -> int:
        """Export the fused txt2img pipeline executables as StableHLO
        (``AotCache``) — wire-or-cut resolution for VERDICT r2 missing #7:
        compilectl writes these, serve boot loads them. The exported set
        follows :meth:`_aot_keys`, so a coalescing unit (SD_BATCH_MAX>1)
        exports the latents-as-argument batch-bucket executables its
        traffic actually runs, not the unused in-graph single path."""
        import os

        from ...core.aot import AotCache

        cache = AotCache(os.path.join(artifact_root, "aot"))
        f = self.pipe.vae_scale
        h, w = self.height // f, self.width // f
        n = 0
        for steps in sorted(self.steps_allowed):
            for shape_key, name in self._aot_keys(steps):
                if shape_key[0] == "batch":
                    b = shape_key[1]
                    fn = (self.pipe._denoise_cache.get(shape_key)
                          or self.pipe._build_pipeline_from_latents(
                              b, h, w, steps))
                    ctx2 = self.pipe.text_encode(
                        jnp.zeros((2 * b, self.seq_len), jnp.int32))
                    args = (self.pipe.unet_params, self.pipe.vae_params,
                            ctx2,
                            jnp.zeros((b, h, w,
                                       self.variant.unet.in_channels),
                                      jnp.float32),
                            jnp.float32(7.5))
                else:
                    fn = self.pipe._denoise_for(1, h, w, steps)
                    ctx2 = self.pipe.text_encode(
                        jnp.zeros((2, self.seq_len), jnp.int32))
                    args = (self.pipe.unet_params, self.pipe.vae_params,
                            ctx2, jax.random.PRNGKey(0), jnp.float32(7.5))
                cache.export(name, fn, args)
                n += 1
        return n

    def warmup(self) -> None:
        for steps in sorted(self.steps_allowed):
            if self._batch_max == 1:
                # warm at batch 1 — the in-graph-latents shape infer() runs
                self.pipe.warm(1, self.height, self.width, steps, self.seq_len)
                continue
            # Coalescer batch buckets (the _aot_keys ladder, starting at
            # b=1): with SD_BATCH_MAX>1 every request — including a solo
            # one — goes through _run_batch → txt2img_batch, whose cache
            # key ('batch', B, ...) names a latents-as-argument executable
            # the single-path pipe.warm() does not build. Warming b=1 here
            # is what makes readiness imply "no post-ready compile"; the
            # in-graph single path is unused in this mode and not warmed.
            for shape_key, _name in self._aot_keys(steps):
                _, b, h, w, _steps = shape_key
                ids = jnp.zeros((b, self.seq_len), jnp.int32)
                lat = jnp.concatenate(
                    [self.pipe.init_latents(i, h, w, steps)
                     for i in range(b)])
                self.pipe.txt2img_batch(ids, ids, lat, height=self.height,
                                        width=self.width, steps=steps,
                                        guidance_scale=self.cfg.guidance_scale)

    def _tokenize(self, text: str) -> np.ndarray:
        with self._tok_lock:
            return tokenize_to_length(self.tokenizer, text, self.seq_len)

    def example_payload(self) -> Dict[str, Any]:
        return {"prompt": "a photo of an astronaut riding a horse", "steps": None}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        from ...models.sd import to_png_base64

        cfg = self.cfg
        prompt = str(payload.get("prompt", payload.get("text", "")))
        steps_raw = payload.get("steps")
        steps = cfg.num_inference_steps if steps_raw is None else int(steps_raw)
        if steps not in self.steps_allowed:
            raise HTTPError(
                400,
                f"steps={steps} not in this deployment's compiled set "
                f"{sorted(self.steps_allowed)} (extend via STEPS_BUCKETS)",
            )
        guidance = float(payload.get("guidance_scale", cfg.guidance_scale))
        seed = int(payload.get("seed", 0))
        ids = self._tokenize(prompt)
        uncond = self._tokenize(str(payload.get("negative_prompt", "")))
        item = {"ids": ids, "uncond": uncond, "seed": seed}
        if self._batch_max > 1:
            img = self._coalesced(item, steps, guidance)
        else:
            img = self.pipe.txt2img(
                jnp.asarray(ids), jnp.asarray(uncond),
                rng=jax.random.PRNGKey(seed),
                height=self.height, width=self.width,
                steps=steps, guidance_scale=guidance,
            )[0]
        return {
            "image_b64": to_png_base64(img),
            "steps": steps,
            "height": self.height,
            "width": self.width,
        }

    # -- request coalescing (SD_BATCH_MAX > 1) ----------------------------

    def _coalesced(self, item: Dict[str, Any], steps: int,
                   guidance: float) -> np.ndarray:
        """Wait one window for same-(steps, guidance) arrivals, then the
        first thread to wake leads: it grabs every matching pending entry
        (up to the cap) and runs them as one batched denoise; grabbed
        followers just wait on their futures. Per-request determinism is
        preserved — each request's init noise comes from ITS seed
        (``pipe.init_latents``), so the image does not depend on the batch
        it landed in."""
        import concurrent.futures
        import time as _time

        fut: concurrent.futures.Future = concurrent.futures.Future()
        key = (steps, guidance)
        entry = (key, item, fut)
        with self._pend_lock:
            self._pending.append(entry)
        _time.sleep(self._coalesce_window_s)
        with self._pend_lock:
            # IDENTITY checks only: entries hold numpy arrays, whose __eq__
            # is elementwise — `entry in list` would raise on the first
            # comparison against a same-key peer
            if any(e is entry for e in self._pending):  # not grabbed: I lead
                # the leader ALWAYS takes its own entry: if pending ever
                # exceeds the cap (serving lane drift, direct infer() use),
                # a batch sliced purely by arrival order could exclude the
                # leader, stranding its future with no owning thread
                others = [e for e in self._pending
                          if e[0] == key and e is not entry]
                batch = [entry] + others[: self._batch_max - 1]
                grabbed = {id(e) for e in batch}
                self._pending = [e for e in self._pending
                                 if id(e) not in grabbed]
            else:
                batch = []
        if batch:
            try:
                imgs = self._run_batch([e[1] for e in batch], steps, guidance)
                for e, img in zip(batch, imgs):
                    e[2].set_result(img)
            except BaseException as exc:
                for e in batch:
                    if not e[2].done():
                        e[2].set_exception(exc)
        return fut.result(timeout=1800)

    def extra_stats(self) -> Dict[str, float]:
        if self._batch_max == 1:
            return {}
        with self._pend_lock:
            waiting = len(self._pending)
            # same lock as _run_batch's increments: no torn (n_b, n_r) pair
            n_b, n_r = self._n_batches, self._n_coalesced
            recent = list(self._recent_batches)
        return {
            "coalesce_batch_max": float(self._batch_max),
            "coalesce_waiting": float(waiting),
            # since-boot totals (for rate math off scraped deltas)
            "coalesced_batches": float(n_b),
            "coalesced_requests": float(n_r),
            "coalesce_occupancy_lifetime": round(n_r / n_b, 3) if n_b else 0.0,
            # mean requests per denoise over the last 32 batches: the
            # CURRENT utilization the weighted KEDA target assumes; near
            # 1.0 under load means the window is too short or traffic too
            # serialized to batch
            "coalesce_occupancy": (round(sum(recent) / len(recent), 3)
                                   if recent else 0.0),
        }

    def _run_batch(self, items, steps: int, guidance: float) -> np.ndarray:
        f = self.pipe.vae_scale
        h, w = self.height // f, self.width // f
        n = len(items)
        b = 1
        while b < n:
            b *= 2
        padded = items + [items[-1]] * (b - n)   # pad slots are discarded
        ids = jnp.asarray(np.stack([np.asarray(i["ids"][0]) for i in padded]))
        unc = jnp.asarray(np.stack([np.asarray(i["uncond"][0]) for i in padded]))
        lat = jnp.concatenate(
            [self.pipe.init_latents(i["seed"], h, w, steps) for i in padded])
        imgs = self.pipe.txt2img_batch(
            ids, unc, lat, height=self.height, width=self.width,
            steps=steps, guidance_scale=guidance)
        with self._pend_lock:
            self._n_batches += 1
            self._n_coalesced += n
            self._recent_batches.append(n)
        if n > 1:
            log.info("sd coalesced %d requests into one batch-%d denoise",
                     n, b)
        return imgs[:n]



# One SD service covers the reference's run-sd.py / run-sd2.py twins (they
# differ only in the Gradio title, reference ``run-sd.py:151`` vs
# ``run-sd2.py:151``) and the SD1.5 geometry.
@register_model("sd")
def _build_sd(cfg: ServeConfig) -> ModelService:
    return SDService(cfg)


@register_model("sd2")
def _build_sd2(cfg: ServeConfig) -> ModelService:
    return SDService(cfg)
