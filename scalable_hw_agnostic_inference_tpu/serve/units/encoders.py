"""Encoder units: bert fill-mask/sentiment + ViT classification (reference run-bert.py / run-vit.py).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
from .common import HashTokenizer, _hf_tokenizer, decode_image

log = logging.getLogger(__name__)


class BertService(ModelService):
    """Sentiment classification — parity with reference ``run-bert.py``."""

    task = "text-classification"
    infer_route = "/predict"

    LABELS = ("NEGATIVE", "POSITIVE")

    def load(self) -> None:
        from ...models import bert

        cfg = self.cfg
        if cfg.model_id in ("", "tiny"):
            mcfg = bert.BertConfig.tiny()
            model = bert.DistilBertClassifier(mcfg, dtype=jnp.float32)
            seq = min(cfg.max_seq_len, mcfg.max_position)
            params = model.init(
                jax.random.PRNGKey(cfg.seed),
                jnp.zeros((1, seq), jnp.int32),
            )
            self.tokenizer = HashTokenizer(mcfg.vocab_size, seq)
        else:
            import torch  # noqa: F401
            from transformers import AutoModelForSequenceClassification

            tm = AutoModelForSequenceClassification.from_pretrained(
                cfg.model_id, token=cfg.hf_token or None
            )
            mcfg = bert.BertConfig.from_hf(tm.config)
            seq = min(cfg.max_seq_len, mcfg.max_position)
            model = bert.DistilBertClassifier(mcfg, dtype=jnp.bfloat16)
            params = bert.params_from_torch(tm, mcfg)
            self.tokenizer = _hf_tokenizer(cfg.model_id, cfg.hf_token)
            if getattr(tm.config, "id2label", None):
                self.LABELS = tuple(
                    tm.config.id2label[i] for i in range(len(tm.config.id2label))
                )
        self.seq = seq
        self.params = jax.device_put(params)
        self.fn = jax.jit(model.apply)

    def _encode(self, text: str):
        if isinstance(self.tokenizer, HashTokenizer):
            ids, mask = self.tokenizer(text)
        else:
            enc = self.tokenizer(
                text, padding="max_length", truncation=True, max_length=self.seq
            )
            ids, mask = np.array(enc["input_ids"]), np.array(enc["attention_mask"])
        return ids[None].astype(np.int32), mask[None].astype(np.int32)

    def example_payload(self) -> Dict[str, Any]:
        return {"text": "i love this framework"}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        ids, mask = self._encode(str(payload.get("text", "")))
        logits = np.asarray(self.fn(self.params, jnp.asarray(ids), jnp.asarray(mask)))
        idx = int(logits[0].argmax())
        probs = jax.nn.softmax(jnp.asarray(logits[0]))
        return {
            "label": self.LABELS[idx % len(self.LABELS)],
            "score": round(float(probs[idx]), 4),
            "logits": [round(float(x), 4) for x in logits[0]],
        }


class ViTService(ModelService):
    """Image classification — parity with reference ``run-vit.py`` (model
    loaded ONCE, not per request; that reference bug is not reproduced)."""

    task = "image-classification"
    infer_route = "/classify"

    def load(self) -> None:
        from ...models import vit

        cfg = self.cfg
        if cfg.model_id in ("", "tiny"):
            mcfg = vit.ViTConfig.tiny()
            model = vit.ViTClassifier(mcfg, dtype=jnp.float32)
            params = model.init(
                jax.random.PRNGKey(cfg.seed),
                jnp.zeros((1, mcfg.image_size, mcfg.image_size, 3)),
            )
            self.labels = {i: f"class_{i}" for i in range(mcfg.n_labels)}
        else:
            from transformers import AutoModelForImageClassification

            tm = AutoModelForImageClassification.from_pretrained(
                cfg.model_id, token=cfg.hf_token or None
            )
            mcfg = vit.ViTConfig.from_hf(tm.config)
            model = vit.ViTClassifier(mcfg, dtype=jnp.bfloat16)
            params = vit.params_from_torch(tm, mcfg)
            self.labels = dict(tm.config.id2label)
        self.mcfg = mcfg
        self.params = jax.device_put(params)
        self.fn = jax.jit(model.apply)

    def example_payload(self) -> Dict[str, Any]:
        return {"image_b64": "random"}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        pixels = decode_image(payload, self.mcfg.image_size)
        logits = np.asarray(self.fn(self.params, jnp.asarray(pixels)))[0]
        top = np.argsort(logits)[::-1][:5]
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits)))
        return {
            "label": self.labels.get(int(top[0]), str(int(top[0]))),
            "top5": [
                {"label": self.labels.get(int(i), str(int(i))),
                 "score": round(float(probs[i]), 4)}
                for i in top
            ],
        }


@register_model("bert")
def _build_bert(cfg: ServeConfig) -> ModelService:
    return BertService(cfg)


@register_model("vit")
def _build_vit(cfg: ServeConfig) -> ModelService:
    return ViTService(cfg)


