"""Causal-LM unit (llama/mistral/deepseek) + VLM/mllama checkpoint loaders (reference run-llama.py, deepseek_model_api.py).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
from .common import _hf_tokenizer

log = logging.getLogger(__name__)


def _load_vlm(cfg: ServeConfig, model_id: str, hf_cfg=None):
    """LLaVA-family checkpoint → (mcfg, params, vcfg, vparams, tokenizer).

    Parity with the reference's multimodal unit
    (``vllm_model_api_m.py:42-66``): one checkpoint carries the vision tower
    + projector and the language model; both convert to flax here (layouts in
    ``models.vlm.params_from_torch`` / ``models.llama.params_from_torch``)
    and persist under the artifact root (hub-less boot, same flow as the
    mllama and causal-lm loaders).
    """
    from ...core import weights as wstore
    from ...models import llama, vlm

    key = f"vlm--{model_id}"

    def _convert():
        nonlocal hf_cfg
        import torch  # noqa: F401
        from transformers import AutoConfig, AutoModelForImageTextToText

        from ...models.convert import cast_f32_to_bf16

        if hf_cfg is None:
            hf_cfg = AutoConfig.from_pretrained(model_id,
                                                token=cfg.hf_token or None)
        tm = AutoModelForImageTextToText.from_pretrained(
            model_id, token=cfg.hf_token or None)
        sd = tm.state_dict()
        del tm
        mcfg = llama.LlamaConfig.from_hf(hf_cfg.text_config)
        vcfg = vlm.VisionTowerConfig.from_hf(hf_cfg, lm_dim=mcfg.dim)
        # strip the llava wrapper prefix so the llama converter sees its
        # usual "model.*"/"lm_head.*" keys (old layout
        # "language_model.model.*", new "model.language_model.*")
        if any(k.startswith("language_model.") for k in sd):
            lm_sd = {k[len("language_model."):]: v for k, v in sd.items()
                     if k.startswith("language_model.")}
        else:
            lm_sd = {k[len("model.language_model."):]: v for k, v in sd.items()
                     if k.startswith("model.language_model.")}
            lm_sd.update({k: v for k, v in sd.items()
                          if k.startswith("lm_head.")})
        tree = {"lm": cast_f32_to_bf16(llama.params_from_torch(lm_sd, mcfg)),
                "vision": cast_f32_to_bf16(vlm.params_from_torch(sd, vcfg))}
        meta = {"text_config": wstore.config_meta(mcfg),
                "vision_config": wstore.config_meta(vcfg)}
        return tree, meta

    tree, meta = wstore.get_or_convert(
        cfg.artifact_root, key, _convert,
        required_meta=("text_config", "vision_config"))
    mcfg = llama.LlamaConfig(**meta["text_config"])
    vcfg = vlm.VisionTowerConfig(**meta["vision_config"])
    tokenizer = _hf_tokenizer(model_id, cfg.hf_token, cache=wstore.aux_dir(
        cfg.artifact_root, key, "tokenizer"))
    return mcfg, tree["lm"], vcfg, tree["vision"], tokenizer


def _load_mllama(cfg: ServeConfig, model_id: str, hf_cfg=None):
    """Mllama (Llama-3.2-Vision) checkpoint → text params for the engine's
    gated-cross-attention path + a jitted vision front-end.

    The actual mllama layout (VERDICT r2 missing #4), not a LLaVA stand-in:
    the tiled two-stage vision encoder + projector produce cross-attention
    states the engine's cross layers attend (``engine.runner._cross_layer``).
    Preprocessing reproduces the HF processor's tiling (canvas selection,
    aspect-preserving resize, pad, split — ``models.mllama.preprocess_tiled``,
    parity-tested); the engine's static buffer holds
    ``cross_seq_len = max_num_tiles * (patches+1)`` rows, of which the first
    ``n_tiles * (patches+1)`` are valid per request (``cross_len``).
    """
    from ...core import weights as wstore
    from ...models import llama, mllama
    from ...models.convert import cast_f32_to_bf16

    def _convert():
        # the torch path: convert the checkpoint + collect preprocessing meta
        import torch  # noqa: F401
        from transformers import AutoConfig, AutoModelForImageTextToText

        hcfg = hf_cfg
        if hcfg is None:
            hcfg = AutoConfig.from_pretrained(model_id,
                                              token=cfg.hf_token or None)
        tm = AutoModelForImageTextToText.from_pretrained(
            model_id, token=cfg.hf_token or None)
        sd = tm.state_dict()
        mcfg = llama.LlamaConfig.from_hf(hcfg.text_config)
        vcfg = mllama.MllamaVisionConfig.from_hf(hcfg.vision_config)
        vparams, pparams = mllama.vision_params_from_torch(sd, vcfg, mcfg.dim)
        if any(k.startswith("language_model.") for k in sd):
            lm_sd = {k[len("language_model."):]: v for k, v in sd.items()
                     if k.startswith("language_model.")}
        else:
            lm_sd = {k[len("model.language_model."):]: v for k, v in sd.items()
                     if k.startswith("model.language_model.")}
            lm_sd.update({k: v for k, v in sd.items()
                          if k.startswith("lm_head.")})
        del tm
        tree = {"text": cast_f32_to_bf16(llama.params_from_torch(lm_sd, mcfg)),
                "vision": cast_f32_to_bf16(vparams),
                "proj": cast_f32_to_bf16(pparams)}
        supported = list(getattr(hcfg.vision_config,
                                 "supported_aspect_ratios", [[1, 1]]))
        # normalization stats from the checkpoint's preprocessor config
        # (real Llama-3.2-Vision ships its own); CLIP stats as the fallback
        img_mean, img_std = mllama.CLIP_MEAN, mllama.CLIP_STD
        try:
            from transformers import AutoImageProcessor

            ip = AutoImageProcessor.from_pretrained(
                model_id, token=cfg.hf_token or None)
            if (getattr(ip, "image_mean", None)
                    and getattr(ip, "image_std", None)):
                img_mean = tuple(ip.image_mean)
                img_std = tuple(ip.image_std)
        except Exception:
            pass
        meta = {"text_config": wstore.config_meta(mcfg),
                "vision_config": wstore.config_meta(vcfg),
                "supported_aspect_ratios": [list(x) for x in supported],
                "image_mean": list(img_mean), "image_std": list(img_std)}
        return tree, meta

    tree, meta = wstore.get_or_convert(
        cfg.artifact_root, f"mllama--{model_id}", _convert,
        required_meta=("text_config", "vision_config",
                       "supported_aspect_ratios", "image_mean", "image_std"))
    mcfg = llama.LlamaConfig(**meta["text_config"])
    vcfg = mllama.MllamaVisionConfig(**{
        **meta["vision_config"],
        "intermediate_layers_indices": tuple(
            meta["vision_config"]["intermediate_layers_indices"])})
    supported = [list(x) for x in meta["supported_aspect_ratios"]]
    img_mean = tuple(meta["image_mean"])
    img_std = tuple(meta["image_std"])
    params, vparams, pparams = tree["text"], tree["vision"], tree["proj"]

    vm = mllama.MllamaVisionModel(vcfg, dtype=jnp.bfloat16)
    proj = mllama.MllamaProjector(vcfg, mcfg.dim, dtype=jnp.bfloat16)
    vparams = jax.device_put(vparams)
    pparams = jax.device_put(pparams)
    P1 = vcfg.n_patches + 1

    @jax.jit
    def _encode(tiles, ar_ids, ar_mask):
        # tiles [1, max_num_tiles, ts, ts, 3] -> [max_tiles*P1, dim] states
        feats = vm.apply(vparams, tiles, ar_ids, ar_mask)
        return proj.apply(pparams, feats)[0].astype(jnp.float32)

    def encode_image(img):
        """PIL image → (cross_states [Lv, dim], n_valid) with HF's tiling
        (``models.mllama.preprocess_tiled``); the valid states are the
        first ``n_tiles * P1`` rows (tiles lead the flattened layout)."""
        tiles, ar_id, n_tiles = mllama.preprocess_tiled(
            img, vcfg, supported, mean=img_mean, std=img_std)
        ar_mask = np.zeros((1, vcfg.max_num_tiles), np.int32)
        ar_mask[0, :n_tiles] = 1
        states = _encode(jnp.asarray(tiles)[None],
                         jnp.asarray([ar_id], jnp.int32),
                         jnp.asarray(ar_mask))
        return np.asarray(states), n_tiles * P1

    lv = vcfg.max_num_tiles * P1
    tokenizer = _hf_tokenizer(model_id, cfg.hf_token, cache=wstore.aux_dir(
        cfg.artifact_root, f"mllama--{model_id}", "tokenizer"))
    return mcfg, params, vcfg, encode_image, lv, tokenizer


def _autoconfig_of(cfg: ServeConfig, model_id: str):
    """One AutoConfig fetch per boot (callers pass it down — VLM detection,
    mllama detection, and the loaders all share it)."""
    if model_id in ("", "tiny"):
        return None
    try:
        from transformers import AutoConfig

        return AutoConfig.from_pretrained(model_id,
                                          token=cfg.hf_token or None)
    except Exception:
        return None


def _is_vlm_checkpoint(cfg: ServeConfig, model_id: str) -> bool:
    hf_cfg = _autoconfig_of(cfg, model_id)
    return (hf_cfg is not None and hasattr(hf_cfg, "vision_config")
            and hasattr(hf_cfg, "text_config"))


def _geometry_models():
    from ...models.llama import LlamaConfig

    return {
        "llama-1b-geometry": LlamaConfig.llama32_1b,
        "llama-3b-geometry": LlamaConfig.llama32_3b,
        "llama-8b-geometry": LlamaConfig.llama3_8b,
        "mistral-7b-geometry": LlamaConfig.mistral_7b,
    }


def _load_causal_lm(cfg: ServeConfig, model_id: str):
    """Shared causal-LM bootstrap for LlamaService and VllmService.

    Returns ``(mcfg, model, params, tokenizer, eos_id, pad_id, byte_tok)``;
    params are host-side (callers place/shard them).
    """
    from ...models import llama
    from ...models.generate import ByteTokenizer

    GEOMETRY_MODELS = _geometry_models()

    if model_id in ("", "tiny"):
        mcfg = llama.LlamaConfig.tiny()
        model = llama.LlamaForCausalLM(mcfg, dtype=jnp.float32)
        params = model.init(
            jax.random.PRNGKey(cfg.seed), jnp.zeros((1, 8), jnp.int32))
        return (mcfg, model, params, ByteTokenizer(),
                ByteTokenizer.eos_id, ByteTokenizer.pad_id, True)

    if model_id in GEOMETRY_MODELS:
        # serving-GEOMETRY tier: full-size architecture, zero weights
        # (models.llama.geometry_params) — boots with no hub/network access,
        # so serving-level load ramps (scripts/breaking_point.py) and the
        # watcher's on-chip sessions can measure the REAL engine/serving
        # stack at real shapes. Throughput is weight-value-independent
        # (bench.py uses the same basis); outputs are meaningless and the
        # unit's model id says "geometry" honestly.
        mcfg = GEOMETRY_MODELS[model_id]()
        model = llama.LlamaForCausalLM(mcfg, dtype=jnp.bfloat16)
        params = llama.geometry_params(mcfg)
        return (mcfg, model, params, ByteTokenizer(),
                ByteTokenizer.eos_id, ByteTokenizer.pad_id, True)

    from ...core import weights as wstore

    def _convert():
        # torch path — the reference's COMPILED_MODEL_ID pull, orbax-shaped
        # (SURVEY.md §5); bf16 on device: the module computes in bf16
        # regardless, and fp32 placement would double HBM
        import torch  # noqa: F401
        from transformers import AutoModelForCausalLM

        from ...models.convert import cast_f32_to_bf16

        tm = AutoModelForCausalLM.from_pretrained(
            model_id, token=cfg.hf_token or None)
        mcfg = llama.LlamaConfig.from_hf(tm.config)
        params = cast_f32_to_bf16(llama.params_from_torch(tm, mcfg))
        del tm
        return params, {"config": wstore.config_meta(mcfg)}

    params, meta = wstore.get_or_convert(
        cfg.artifact_root, f"causal-lm--{model_id}", _convert,
        required_meta=("config",))
    mcfg = llama.LlamaConfig(**meta["config"])
    model = llama.LlamaForCausalLM(mcfg, dtype=jnp.bfloat16)
    tokenizer = _hf_tokenizer(model_id, cfg.hf_token, cache=wstore.aux_dir(
        cfg.artifact_root, f"causal-lm--{model_id}", "tokenizer"))
    # `is not None` (not truthiness): token id 0 is a legitimate id
    eos = tokenizer.eos_token_id
    if eos is None:
        raise ValueError(f"tokenizer for {model_id} has no eos_token_id")
    pad = tokenizer.pad_token_id
    return (mcfg, model, params, tokenizer, int(eos),
            int(pad) if pad is not None else int(eos), False)


class LlamaService(ModelService):
    """Text generation — parity with reference ``run-llama.py`` (Llama-3/
    Mistral) and ``deepseek_model_api.py`` (generic causal LM + /benchmark).

    One jitted generate per (prompt-bucket, max-new-tokens) shape; the
    smallest bucket is compile-warmed before readiness, larger buckets warm
    lazily on first use. TP via MESH_SPEC (e.g. ``tp=4``): weights are placed
    with the declarative Megatron rules table and XLA inserts the collectives.
    """

    task = "text-generation"
    infer_route = "/generate"
    # multi-host unit contract: EVERY device entry (infer, /sentiment,
    # default warmup) funnels through generate_text, so mirroring it covers
    # the whole surface (deploy/units/llama-mh-tpu-deploy.yaml)
    supports_multihost = True
    mirror_methods = ("generate_text",)

    def load(self) -> None:
        from ...core.bucketing import BucketRegistry, pow2_buckets
        from ...core.mesh import build_mesh
        from ...models import llama
        from ...models.generate import make_generate

        cfg = self.cfg
        (mcfg, self.model, params, self.tokenizer,
         self.eos_id, self.pad_id, self._byte_tok) = _load_causal_lm(
            cfg, cfg.model_id)
        self.mcfg = mcfg

        if cfg.quantization == "int8":
            # weight-only int8 at boot (the engine units' vllm_config knob,
            # env-shaped for this service): halves decode HBM traffic and is
            # what fits an 8B distill on one 16 GiB v5e chip
            # (deploy/gen_units.py deepseek-tpu unit; core.budget accounting)
            from ...ops.quant import quantize_params_tree

            params = quantize_params_tree(params)
            self.model = llama.LlamaForCausalLM(
                mcfg, dtype=self.model.dtype, quant=True)

        if cfg.mesh_spec:
            from ...parallel.sharding import shard_pytree

            mesh = build_mesh(cfg.mesh_spec)
            params = shard_pytree(params, mesh, llama.tp_rules())
        else:
            params = jax.device_put(params)
        self.params = params

        max_prompt = min(cfg.max_seq_len, mcfg.max_seq_len - cfg.max_new_tokens)
        if max_prompt < 1:
            raise ValueError(
                f"MAX_NEW_TOKENS={cfg.max_new_tokens} leaves no prompt room "
                f"within the model's max_seq_len={mcfg.max_seq_len}"
            )
        self.buckets = BucketRegistry(pow2_buckets(min(32, max_prompt), max_prompt))
        self._gen = {}
        self._make_generate = lambda bucket: make_generate(
            self.model, self.mcfg,
            prompt_bucket=bucket, max_new_tokens=cfg.max_new_tokens,
            eos_id=self.eos_id, pad_id=self.pad_id,
            cache_dtype=jnp.bfloat16 if cfg.device == "tpu" else jnp.float32,
        )

    def _gen_for(self, bucket: int):
        if bucket not in self._gen:
            self._gen[bucket] = self._make_generate(bucket)
        return self._gen[bucket]

    def _encode(self, text: str):
        if self._byte_tok:
            ids, n = self.tokenizer.encode(text, self.buckets.max)
            ids = ids[:n]
        else:
            ids = np.asarray(
                self.tokenizer(text, truncation=True, max_length=self.buckets.max)[
                    "input_ids"
                ],
                np.int32,
            )
        if len(ids) == 0:
            raise HTTPError(400, "empty prompt")
        bucket = self.buckets.bucket_for(len(ids))
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, : len(ids)] = ids
        return padded, np.array([len(ids)], np.int32), bucket

    def _decode(self, ids) -> str:
        ids = [int(i) for i in ids if int(i) not in (self.pad_id,) and int(i) != self.eos_id]
        if self._byte_tok:
            return self.tokenizer.decode(ids)
        return self.tokenizer.decode(ids, skip_special_tokens=True)

    def example_payload(self) -> Dict[str, Any]:
        return {"prompt": "the quick brown fox", "temperature": 0.0}

    def generate_text(self, prompt: str, temperature=1.0, top_k=0, top_p=1.0,
                      max_new_tokens: Optional[int] = None, seed: int = 0):
        if max_new_tokens is not None and int(max_new_tokens) > self.cfg.max_new_tokens:
            raise HTTPError(
                400,
                f"max_new_tokens={max_new_tokens} exceeds this deployment's "
                f"compiled cap MAX_NEW_TOKENS={self.cfg.max_new_tokens}",
            )
        ids, n, bucket = self._encode(prompt)
        fn = self._gen_for(bucket)
        res = fn(self.params, jnp.asarray(ids), jnp.asarray(n),
                 jax.random.PRNGKey(seed), float(temperature), int(top_k),
                 float(top_p))
        toks = np.asarray(res.tokens)[0]
        if max_new_tokens is not None:
            toks = toks[: max(int(max_new_tokens), 0)]
        n_gen = int(np.sum(toks != self.pad_id))
        return self._decode(toks), n_gen

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        prompt = str(payload.get("prompt", payload.get("text", "")))
        text, n_gen = self.generate_text(
            prompt,
            temperature=float(payload.get("temperature", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            max_new_tokens=payload.get("max_new_tokens"),
            seed=int(payload.get("seed", 0)),
        )
        return {"generated_text": text, "n_tokens": n_gen}

    def extra_routes(self):
        def sentiment(request):
            # reference run-llama.py's bonus /sentiment prompt-template
            # endpoint (reference ``app/run-llama.py:48-51,82-85``)
            body = request.json()
            text = str(body.get("text", ""))
            prompt = (
                "Classify the sentiment of the following review as "
                f"Positive or Negative.\nReview: {text}\nSentiment:"
            )
            out, _ = self.generate_text(prompt, temperature=0.0)
            return {"sentiment": out.strip().split("\n")[0]}

        return [("/sentiment", ("POST",), sentiment)]


@register_model("llama")
def _build_llama(cfg: ServeConfig) -> ModelService:
    return LlamaService(cfg)


# Same causal-LM service covers the reference's Mistral and DeepSeek-distill
# units (reference ``app/run-llama.py`` serves both families by MODEL_ID;
# ``app/deepseek_model_api.py`` is its /benchmark-bearing twin).
@register_model("mistral")
def _build_mistral(cfg: ServeConfig) -> ModelService:
    return LlamaService(cfg)


@register_model("deepseek")
def _build_deepseek(cfg: ServeConfig) -> ModelService:
    return LlamaService(cfg)


