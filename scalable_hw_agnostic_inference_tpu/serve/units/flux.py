"""Flux txt2img unit with sub-mesh packing (reference flux_model_api.py).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
import dataclasses

from .common import HashTokenizer, _hf_tokenizer, tokenize_to_length

log = logging.getLogger(__name__)


class FluxService(ModelService):
    """Flux txt2img — parity with reference ``flux_model_api.py``.

    The reference pins CLIP+VAE / T5-TP8 / transformer-TP8 to overlapping
    NeuronCore ranges of one 16-core host (``app/flux_model_api.py:128-140,
    298-320``); here SUBMESH="a:b" gives the transformer its TP slice and the
    encoders+VAE live on the remaining devices (``core.mesh.submesh``). One
    jitted scan runs the whole denoise; flux-dev guidance is an embedding,
    not CFG, so no batch doubling.
    """

    task = "text-to-image"
    infer_route = "/genimage"

    def load(self) -> None:
        from ...core.device import local_devices
        from ...core.mesh import build_mesh, parse_submesh, submesh
        from ...models import clip, flux, t5
        from ...models.flux_pipeline import FluxPipeline
        from ...models.vae import AutoencoderKL, VAEConfig

        cfg = self.cfg
        devices = local_devices()
        sub = parse_submesh(cfg.submesh) if cfg.submesh else None
        if sub is not None:
            tf_devices = submesh(sub[0], sub[1], devices)
            rest = [d for d in devices if d not in tf_devices] or devices[:1]
        else:
            tf_devices, rest = devices, devices[:1]
        enc_dev = rest[0]

        if cfg.model_id in ("", "tiny"):
            fcfg = flux.FluxConfig.tiny()
            tcfg = t5.T5Config.tiny()
            ccfg = clip.ClipTextConfig.tiny()
            vcfg = VAEConfig.tiny()
            t5m = t5.T5Encoder(tcfg)
            t5p = t5m.init(jax.random.PRNGKey(cfg.seed),
                           jnp.zeros((1, 8), jnp.int32))
            clipm = clip.ClipTextEncoder(ccfg)
            clipp = clipm.init(jax.random.PRNGKey(cfg.seed + 1),
                               jnp.zeros((1, 8), jnp.int32))
            model = flux.FluxTransformer(fcfg, dtype=jnp.float32)
            h = w = 8
            fparams = model.init(
                jax.random.PRNGKey(cfg.seed + 2),
                jnp.zeros((1, (h // 2) * (w // 2), fcfg.in_channels)),
                jnp.zeros((1, 8, fcfg.t5_dim)),
                jnp.zeros((1, fcfg.clip_dim)),
                jnp.zeros((1,)), jnp.zeros((1,)),
                flux.make_ids(1, 8, h, w))
            vae = AutoencoderKL(vcfg)
            vparams = vae.init(jax.random.PRNGKey(cfg.seed + 3),
                               jnp.zeros((1, 4, 4, vcfg.latent_channels)))
            self.t5_tok = HashTokenizer(tcfg.vocab_size, 16)
            self.clip_tok = HashTokenizer(ccfg.vocab_size, ccfg.max_position)
            self.t5_len, self.clip_len = 16, ccfg.max_position
            self.height = self.width = 32  # vae_scale 2 * patch 2 * 8 lat
            from ...models.flow_match import FlowMatchConfig

            schedule = FlowMatchConfig()
        else:
            import os

            from safetensors.torch import load_file
            from transformers import CLIPTextModel, T5EncoderModel

            from ...models import sd as sd_mod
            from ...models import vae as vae_mod
            from ...models.convert import cast_f32_to_bf16

            root = sd_mod.resolve_checkpoint_dir(cfg.model_id, cfg.hf_token)
            fcfg = flux.FluxConfig.flux_dev()
            tmt = T5EncoderModel.from_pretrained(root, subfolder="text_encoder_2")
            tcfg = t5.T5Config.from_hf(tmt.config)
            t5m = t5.T5Encoder(tcfg, dtype=jnp.bfloat16)
            t5p = cast_f32_to_bf16(t5.params_from_torch(tmt, tcfg))
            del tmt
            tmc = CLIPTextModel.from_pretrained(root, subfolder="text_encoder")
            ccfg = clip.ClipTextConfig.from_hf(tmc.config)
            clipm = clip.ClipTextEncoder(ccfg)
            clipp = clip.params_from_torch(tmc, ccfg)
            del tmc
            # BFL single-file transformer weights; HF repo stores them under
            # transformer/ in diffusers layout and flux1-dev.safetensors at
            # the root — we consume the BFL layout (models.flux converter)
            import glob
            import json

            # variant-agnostic: flux1-dev / flux1-schnell single-file weights;
            # schnell has no guidance embedding (detected by key presence).
            # Without the single file, a plain diffusers snapshot's
            # transformer/ subfolder (possibly sharded) loads through the
            # key-map converter (VERDICT r2 #7)
            matches = sorted(glob.glob(os.path.join(root, "flux1-*.safetensors")))
            if matches:
                bfl_sd = load_file(matches[0])
            else:
                shards = sorted(glob.glob(os.path.join(
                    root, "transformer", "diffusion_pytorch_model*.safetensors")))
                if not shards:
                    raise FileNotFoundError(
                        f"no flux1-*.safetensors and no transformer/ weights "
                        f"under {root}")
                dsd = {}
                for sh in shards:
                    dsd.update(load_file(sh))
                bfl_sd = flux.bfl_from_diffusers(dsd)
                del dsd
            fcfg = dataclasses.replace(
                fcfg, guidance_embed="guidance_in.in_layer.weight" in bfl_sd)
            fparams = cast_f32_to_bf16(flux.params_from_torch(bfl_sd, fcfg))
            del bfl_sd
            # sigma schedule from the checkpoint's diffusers scheduler config
            # when present; otherwise schnell (no guidance embed) wants static
            # shift=1.0 while dev keeps the dynamic-shift defaults
            from ...models.flow_match import FlowMatchConfig

            sched_path = os.path.join(root, "scheduler",
                                      "scheduler_config.json")
            if os.path.exists(sched_path):
                with open(sched_path) as f:
                    sc = json.load(f)
                schedule = FlowMatchConfig(
                    num_train_timesteps=sc.get("num_train_timesteps", 1000),
                    shift=sc.get("shift", 1.0),
                    use_dynamic_shifting=sc.get("use_dynamic_shifting", False),
                    base_seq_len=sc.get("base_image_seq_len", 256),
                    max_seq_len=sc.get("max_image_seq_len", 4096),
                    base_shift=sc.get("base_shift", 0.5),
                    max_shift=sc.get("max_shift", 1.15))
            elif fcfg.guidance_embed:
                schedule = FlowMatchConfig()
            else:
                schedule = FlowMatchConfig(use_dynamic_shifting=False,
                                           shift=1.0)
            with open(os.path.join(root, "vae", "config.json")) as f:
                vcfg = vae_mod.VAEConfig.from_hf(json.load(f))
            vparams = vae_mod.params_from_torch(
                sd_mod.load_torch_state(os.path.join(root, "vae")), vcfg)
            self.t5_tok = _hf_tokenizer(f"{root}/tokenizer_2", cfg.hf_token)
            self.clip_tok = _hf_tokenizer(f"{root}/tokenizer", cfg.hf_token)
            # schnell's max_sequence_length is 256 (dev: 512)
            self.t5_len = 512 if fcfg.guidance_embed else 256
            self.clip_len = ccfg.max_position
            self.height, self.width = cfg.height, cfg.width

        t5p = jax.device_put(t5p, enc_dev)
        clipp = jax.device_put(clipp, enc_dev)
        vparams = jax.device_put(vparams, enc_dev)
        mesh = None
        if len(tf_devices) > 1:
            mesh = build_mesh(f"tp={len(tf_devices)}", devices=tf_devices)
            from ...parallel.sharding import shard_pytree

            fparams = shard_pytree(fparams, mesh, flux.tp_rules())
        else:
            fparams = jax.device_put(fparams, tf_devices[0])

        self.steps_allowed = {cfg.num_inference_steps}
        if cfg.steps_buckets:
            self.steps_allowed |= {
                int(s) for s in cfg.steps_buckets.split(",") if s.strip()
            }
        t5_fn = jax.jit(lambda ids: t5m.apply(t5p, ids))
        clip_fn = jax.jit(lambda ids: clipm.apply(clipp, ids)[1])
        self.pipe = FluxPipeline(
            fcfg, fparams, vcfg, vparams, t5_fn, clip_fn, schedule=schedule,
            dtype=jnp.float32 if cfg.model_id in ("", "tiny") else jnp.bfloat16,
            mesh=mesh, encoder_device=enc_dev)

    def warmup(self) -> None:
        # same closed compiled-steps policy as SDService: every allowed steps
        # value is warmed; clients cannot force request-time compiles
        for steps in sorted(self.steps_allowed):
            self.pipe.warm(1, self.height, self.width, steps,
                           self.t5_len, self.clip_len)

    def example_payload(self) -> Dict[str, Any]:
        return {"prompt": "a watercolor fox", "steps": None}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        from ...models.sd import to_png_base64

        prompt = str(payload.get("prompt", ""))
        steps_raw = payload.get("steps")
        steps = (self.cfg.num_inference_steps if steps_raw is None
                 else int(steps_raw))
        if steps not in self.steps_allowed:
            raise HTTPError(
                400,
                f"steps={steps} not in this deployment's compiled set "
                f"{sorted(self.steps_allowed)} (extend via STEPS_BUCKETS)")
        guidance = float(payload.get("guidance_scale",
                                     payload.get("guidance",
                                                 self.cfg.guidance_scale)))
        seed = int(payload.get("seed", 0))
        imgs = self.pipe.txt2img(
            jnp.asarray(tokenize_to_length(self.t5_tok, prompt, self.t5_len)),
            jnp.asarray(tokenize_to_length(self.clip_tok, prompt,
                                           self.clip_len)),
            rng=jax.random.PRNGKey(seed), height=self.height,
            width=self.width, steps=steps, guidance=guidance)
        return {"image_b64": to_png_base64(imgs[0]), "steps": steps,
                "height": self.height, "width": self.width}


@register_model("flux")
def _build_flux(cfg: ServeConfig) -> ModelService:
    return FluxService(cfg)
