"""YOLOS object-detection unit (reference run-yolo.py /detectobj).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
from .common import IMAGENET_MEAN, IMAGENET_STD, decode_image

log = logging.getLogger(__name__)


class YolosService(ModelService):
    """Object detection — parity with reference ``run-yolo.py`` (whose
    ``/detectobj`` handler calls an undefined function, reference
    ``app/run-yolo.py:68``; implemented for real here).
    """

    task = "object-detection"
    infer_route = "/detectobj"

    def load(self) -> None:
        from ...models import yolos

        cfg = self.cfg
        if cfg.model_id in ("", "tiny"):
            mcfg = yolos.YolosConfig.tiny()
            model = yolos.YolosForObjectDetection(mcfg)
            params = model.init(
                jax.random.PRNGKey(cfg.seed),
                jnp.zeros((1, *mcfg.image_size, 3)))
            self.id2label = {i: f"class_{i}" for i in range(mcfg.n_labels - 1)}
        else:
            import torch  # noqa: F401
            from transformers import YolosForObjectDetection as HFYolos

            tm = HFYolos.from_pretrained(cfg.model_id, token=cfg.hf_token or None)
            mcfg = yolos.YolosConfig.from_hf(tm.config)
            model = yolos.YolosForObjectDetection(mcfg, dtype=jnp.bfloat16)
            params = yolos.params_from_torch(tm, mcfg)
            self.id2label = dict(getattr(tm.config, "id2label", {}) or {})
            del tm
        self.mcfg = mcfg
        self.params = jax.device_put(params)
        self.fn = jax.jit(model.apply)
        self._post = yolos.postprocess

    def example_payload(self) -> Dict[str, Any]:
        return {"image_b64": "random", "threshold": 0.5}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        H, W = self.mcfg.image_size
        # HF YolosImageProcessor normalizes with ImageNet stats, not 0.5/0.5
        arr = decode_image(payload, H, W, mean=IMAGENET_MEAN, std=IMAGENET_STD)
        thr = float(payload.get("threshold", 0.9))
        logits, boxes = self.fn(self.params, jnp.asarray(arr))
        dets = self._post(np.asarray(logits)[0], np.asarray(boxes)[0], thr,
                          W, H, self.id2label)
        return {"detections": dets, "count": len(dets)}


@register_model("yolo")
def _build_yolo(cfg: ServeConfig) -> ModelService:
    return YolosService(cfg)
