"""T5 embedding unit (reference t5_model_api.py).

Split out of the former serve/services.py monolith (VERDICT r3 weak #5);
behavior unchanged — serve/services.py re-exports everything for
compatibility, and registration happens on import (models.registry).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.registry import register_model
from ...utils.env import ServeConfig
from ..app import ModelService
from ..asgi import HTTPError
from .common import HashTokenizer, _hf_tokenizer

log = logging.getLogger(__name__)


class T5EmbedService(ModelService):
    """Mean-pooled sentence embeddings — parity with reference
    ``t5_model_api.py`` (TP-sharded T5-v1.1 encoder, shard-selective load
    ``:27``, mean-pool readout ``:44``). TP via MESH_SPEC uses the
    declarative rules table in ``models.t5`` instead of the reference's
    hand-sharded ``parallel_model_load``.
    """

    task = "embeddings"
    infer_route = "/embed"

    def load(self) -> None:
        from ...models import t5

        cfg = self.cfg
        if cfg.model_id in ("", "tiny"):
            mcfg = t5.T5Config.tiny()
            model = t5.T5Encoder(mcfg)
            seq = min(cfg.max_seq_len, 64)
            params = model.init(
                jax.random.PRNGKey(cfg.seed),
                jnp.zeros((1, seq), jnp.int32), jnp.ones((1, seq), jnp.int32))
            self.tokenizer = HashTokenizer(mcfg.vocab_size, seq)
        else:
            import torch  # noqa: F401
            from transformers import T5EncoderModel

            from ...models.convert import cast_f32_to_bf16

            tm = T5EncoderModel.from_pretrained(
                cfg.model_id, token=cfg.hf_token or None)
            mcfg = t5.T5Config.from_hf(tm.config)
            model = t5.T5Encoder(mcfg, dtype=jnp.bfloat16)
            params = cast_f32_to_bf16(t5.params_from_torch(tm, mcfg))
            del tm
            self.tokenizer = _hf_tokenizer(cfg.model_id, cfg.hf_token)
            seq = min(cfg.max_seq_len, 512)
        self.seq = seq
        if cfg.mesh_spec:
            from ...core.mesh import build_mesh
            from ...parallel.sharding import shard_pytree

            mesh = build_mesh(cfg.mesh_spec)
            params = shard_pytree(params, mesh, t5.tp_rules())
        else:
            params = jax.device_put(params)
        self.params = params

        def embed(p, ids, mask):
            hidden = model.apply(p, ids, mask)
            return t5.mean_pool(hidden, mask)

        self.fn = jax.jit(embed)

    def _encode(self, text: str):
        if isinstance(self.tokenizer, HashTokenizer):
            ids, mask = self.tokenizer(text)
        else:
            enc = self.tokenizer(text, padding="max_length", truncation=True,
                                 max_length=self.seq)
            ids = np.array(enc["input_ids"])
            mask = np.array(enc["attention_mask"])
        return ids[None].astype(np.int32), mask[None].astype(np.int32)

    def example_payload(self) -> Dict[str, Any]:
        return {"text": "embed me"}

    def infer(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        text = payload.get("text", payload.get("prompt"))
        if text is None:
            raise HTTPError(400, "missing 'text'")
        ids, mask = self._encode(str(text))
        emb = np.asarray(self.fn(self.params, jnp.asarray(ids), jnp.asarray(mask)))
        return {"embedding": emb[0].tolist(), "dim": int(emb.shape[-1])}


@register_model("t5")
def _build_t5(cfg: ServeConfig) -> ModelService:
    return T5EmbedService(cfg)
